"""Adaptive (reactive) scheduling: rescale the job — drained OR under fire.

Analog of ``runtime/scheduler/adaptive/AdaptiveScheduler.java:146``
(FLIP-160): a state machine — Created → WaitingForResources → Executing →
Restarting → Finished/Failed — that sizes the job to whatever slots exist.
``declare_slots(n)`` (the reactive-mode resource declaration) triggers a
rescale: take a savepoint, cancel, re-split every keyed vertex's state to
the new parallelism through the key-group redistribution path, and redeploy.

:class:`ReactiveAutoscaler` (ISSUE-14) closes the loop for the
BACKPRESSURED case: driven by the job's own backpressure / queue-depth /
alignment gauges (and the per-(source, hop) latency p99s), it rescales
via an **unaligned checkpoint of the running job** — no drain — with the
persisted in-flight channel state redistributed by record key
(``state/redistribute.redistribute_channel_state``, the FLIP-76
follow-on).  The rescale lifecycle is a supervised failure domain: a
bounded deadline with rollback to the pre-rescale checkpoint, idempotent
re-trigger after a kill inside the window (chaos point
``rescale.redistribute``; ``testing.chaos.KillDuringRescale``), and a
``rescale`` trace span covering trigger→checkpoint→redistribute→redeploy→
first-output.

Rescale contract: sources must have STABLE splits (split count independent
of job parallelism — files, log partitions); their offsets carry over
unchanged.  Keyed vertex state is merged across old subtasks and re-split
by key-group range (``StateAssignmentOperation.reDistributeKeyedStates``).

Time discipline (PR-4 convention): every cooldown / deadline / elapsed
DECISION in this module reads the injectable ``utils/clock.py`` seam
through :class:`~flink_tpu.utils.clock.MonotoneElapsed`, so a chaos
``ClockSkew`` backward step can neither un-expire a rescale deadline nor
turn the autoscaler's cooldown into a rescale storm; loop pacing uses
``clock.sleep`` (a raw passthrough — scheduling, not a decision).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core import keygroups

from flink_tpu.cluster.failover import (FixedDelayRestartStrategy,
                                        RestartStrategy)
from flink_tpu.cluster.minicluster import JobResult, MiniCluster
from flink_tpu.graph.stream_graph import ExecutionPlan
from flink_tpu.observability import tracing
from flink_tpu.state.redistribute import (redistribute_channel_state,
                                          snapshot_operator_class,
                                          split_keyed_snapshot)
from flink_tpu.state_processor.savepoint import (_is_keyed,
                                                 _merged_operator_snapshot)
from flink_tpu.testing import chaos
from flink_tpu.utils import clock
from flink_tpu.utils.clock import MonotoneElapsed


class SchedulerStates:
    CREATED = "Created"
    WAITING_FOR_RESOURCES = "WaitingForResources"
    EXECUTING = "Executing"
    RESTARTING = "Restarting"
    FINISHED = "Finished"
    FAILED = "Failed"
    CANCELED = "Canceled"


def _split_member(member: Dict[str, Any], max_parallelism: int,
                  n: int) -> List[Dict[str, Any]]:
    # operators with their own rescale split/merge pair (window aggregate,
    # session windows, CEP per-key state, two-phase-commit sinks) dispatch
    # through the ONE kind table the savepoint merge also uses
    cls = snapshot_operator_class(member)
    if cls is not None:
        return cls.split_snapshot(member, max_parallelism, n)
    if _is_keyed(member):
        fields = sorted({k for k in member
                         if k.startswith("state.") or k == "leaves"})
        return split_keyed_snapshot(member, fields, max_parallelism, n)
    # stateless / non-keyed member: subtask 0 keeps it, others start fresh
    return [member] + [{} for _ in range(n - 1)]


def _is_collect_sink_member(m: Any) -> bool:
    return isinstance(m, dict) and set(m) == {"batches"} \
        and isinstance(m["batches"], list)


def _union_shared_sink_members(ops: List[Dict[str, Any]], key_column: str,
                               max_parallelism: int) -> None:
    """Exactly-once merge for SHARED collect-sink chain members, in place.

    One CollectSink instance is shared by every subtask, so each
    subtask's snapshot is the shared row list AS OF ITS OWN barrier —
    under an unaligned cut those moments differ, and keeping any single
    copy is inconsistent: a row fired by subtask i between copy j's
    snapshot and i's own is present in i's copy and EVICTED from i's
    pane state, so dropping i's copy loses it forever.  The consistent
    composition is per-key owner filtering: subtask i's copy contributes
    exactly the rows of keys i OWNS (i's own fires run on i's thread, so
    they are in i's copy iff they preceded i's snapshot iff their pane
    state is gone) — union those slices and park the result on subtask
    0's member (the non-keyed merge keeps subtask 0), emptying the rest.
    Members without the key column fall back untouched."""
    P = len(ops)
    member_keys = sorted(k for k in ops[0]
                         if k.startswith("op") and k[2:].isdigit()
                         and all(_is_collect_sink_member(o.get(k))
                                 for o in ops if isinstance(o, dict)))
    for mk in member_keys:
        if any(key_column not in cols for o in ops
               for cols, _ts in o[mk]["batches"]):
            continue                # unkeyed rows: keep old behavior
        kept = []
        for i, o in enumerate(ops):
            for cols, ts in o[mk]["batches"]:
                keys = np.asarray(cols[key_column])
                mine = keygroups.route_raw_keys(
                    keys, P, max_parallelism) == i
                if mine.any():
                    kept.append((
                        {c: np.asarray(v)[mine] for c, v in cols.items()},
                        None if ts is None else np.asarray(ts)[mine]))
        for i, o in enumerate(ops):
            o[mk] = {"batches": kept} if i == 0 else {}


def _channel_sections(old_subs: List[Any]) -> List[Any]:
    return [(sub or {}).get("channel_state") if isinstance(sub, dict)
            else None for sub in old_subs]


def _has_inflight(sections: List[Any]) -> bool:
    for cs in sections:
        els = cs.get("elements", []) if isinstance(cs, dict) else cs
        if els:
            return True
    return False


def rescale_snapshot(snapshot: Dict[str, Any], plan: ExecutionPlan,
                     new_counts: Dict[str, int]) -> Dict[str, Any]:
    """A MiniCluster checkpoint taken at one parallelism -> restorable at
    another (the StateAssignmentOperation analog), INCLUDING unaligned
    checkpoints: persisted in-flight channel state (v2 sections) is
    decoded per element and re-routed by the record's own key into the
    new key-group ranges (``redistribute_channel_state`` — the FLIP-76
    follow-on, ``reDistributeKeyedStates`` for in-flight data); non-keyed
    and broadcast in-flight elements replay on their downstream's subtask
    0.  Legacy v1 sections with non-empty elements still fail loudly
    (``ChannelStateRescaleError``) — they carry no routing metadata.

    Fires the ``rescale.redistribute`` chaos point once per genuine
    rescale, BEFORE any state is transformed: a schedule killing/stalling
    here lands inside the rescale window with the pre-rescale checkpoint
    still intact, so the lifecycle's re-trigger is idempotent."""
    out: Dict[str, Any] = {}
    by_uid = {v.uid: v for v in plan.vertices}
    rescaled = sorted(
        uid for uid, entry in snapshot.items()
        if not uid.startswith("__") and uid in by_uid
        and not by_uid[uid].is_source and new_counts.get(uid) is not None
        and isinstance(entry, dict)
        and len(entry.get("subtasks", [])) != new_counts[uid])
    if rescaled:
        # the chaos seam of the rescale window (KillDuringRescale prey)
        chaos.fire("rescale.redistribute", uids=rescaled)
    producers: Dict[str, List[str]] = {}
    for u in plan.vertices:
        for e in u.out_edges:
            producers.setdefault(plan.by_id[e.target_id].uid,
                                 []).append(u.uid)

    def upstream_changed(uid: str) -> bool:
        """Did this vertex's INPUT topology change — i.e. does any
        producer's subtask count differ from the snapshot's?  A vertex
        whose own count AND whose producers' counts are unchanged keeps
        its channel state positionally (physical indices stay valid)."""
        for pu in producers.get(uid, []):
            pe = snapshot.get(pu)
            n_old = (len(pe.get("subtasks", []))
                     if isinstance(pe, dict) else None)
            n_want = new_counts.get(pu)
            if n_old is not None and n_want is not None \
                    and n_old != n_want:
                return True
        return False

    for uid, entry in snapshot.items():
        if uid.startswith("__"):
            out[uid] = entry
            continue
        v = by_uid.get(uid)
        n_new = new_counts.get(uid)
        if v is None or n_new is None:
            out[uid] = entry
            continue
        old_subs = entry.get("subtasks", []) if isinstance(entry, dict) else []
        if v.is_source:
            if len(old_subs) != n_new:
                raise ValueError(
                    f"rescale: source {uid!r} split count changed "
                    f"({len(old_subs)} -> {n_new}); adaptive rescale needs "
                    f"stable-split sources (files / log partitions)")
            out[uid] = entry
            continue
        sections = _channel_sections(old_subs)
        if len(old_subs) == n_new:
            if rescaled and _has_inflight(sections) \
                    and upstream_changed(uid):
                # the vertex keeps its parallelism but its UPSTREAM
                # rescales: physical channel indices die with the old
                # input topology — re-route its in-flight elements too
                # (keyed elements land back on the same subtask: the
                # key-group assignment is the same function).  A vertex
                # whose inputs are untouched keeps positional replay.
                new_secs = redistribute_channel_state(sections, n_new)
                entry = dict(entry)
                entry["subtasks"] = [
                    dict(sub or {}, channel_state=new_secs[i])
                    for i, sub in enumerate(entry["subtasks"])]
            out[uid] = entry
            continue
        new_secs = (redistribute_channel_state(sections, n_new)
                    if _has_inflight(sections) else None)
        # shared collect-sink members: per-key owner-filtered union BEFORE
        # the merge (keep-subtask-0 would drop rows other owners already
        # evicted from their pane state — see _union_shared_sink_members)
        kc = kmaxp = None
        for u in plan.vertices:
            for e in u.out_edges:
                if plan.by_id[e.target_id].uid == uid \
                        and e.partitioning == "hash" and e.key_column:
                    kc, kmaxp = e.key_column, u.max_parallelism
        if kc is not None and old_subs \
                and all(isinstance(s, dict) and isinstance(
                    s.get("operator"), dict) for s in old_subs):
            ops = [dict(s["operator"]) for s in old_subs]
            _union_shared_sink_members(ops, kc, kmaxp)
            entry = dict(entry)
            entry["subtasks"] = [dict(s, operator=o)
                                 for s, o in zip(old_subs, ops)]
        # strict: a keyed member that cannot merge must FAIL the rescale
        # (the lifecycle retries / rolls back), never silently redeploy
        # with only subtask 0's share of the state
        merged = _merged_operator_snapshot(entry, strict=True)
        inner = merged.get("operator", merged)
        maxp = v.max_parallelism
        member_keys = [k for k in inner
                       if k.startswith("op") and k[2:].isdigit()]
        parts: List[Dict[str, Any]]
        if member_keys:
            split_members = {mk: _split_member(inner[mk], maxp, n_new)
                             for mk in member_keys}
            passthrough = {k: v2 for k, v2 in inner.items()
                           if k not in member_keys}
            parts = [dict(passthrough,
                          **{mk: split_members[mk][i] for mk in member_keys})
                     for i in range(n_new)]
        else:
            parts = _split_member(inner, maxp, n_new)
        wrapped = []
        for p in parts:
            if isinstance(merged, dict) and "operator" in merged:
                w = {k: v2 for k, v2 in merged.items() if k != "operator"}
                w["operator"] = p
                wrapped.append(w)
            else:
                wrapped.append({"operator": p, "valve": None}
                               if "operator" not in p else p)
        # subtask snapshots are {"operator": ..., "valve": ...} shaped
        subs = [w if "operator" in w else {"operator": w} for w in wrapped]
        if new_secs is not None:
            for i, sub in enumerate(subs):
                sub["channel_state"] = new_secs[i]
        out[uid] = {"subtasks": subs}
    return out


def counts_for_plan(plan: ExecutionPlan) -> Dict[str, int]:
    """Per-vertex subtask count the deploying cluster will use — THE
    deploy-side implementation (``distributed.subtask_counts_of``), not a
    mirror of it: a rescale split to any other count would restore whole
    key-group ranges into subtasks that never deploy."""
    from flink_tpu.cluster.distributed import subtask_counts_of
    return subtask_counts_of(plan)[0]


def maybe_rescale_restore(restore: Optional[Dict[str, Any]],
                          plan: ExecutionPlan) -> Optional[Dict[str, Any]]:
    """Restore-time guard shared by MiniCluster / ProcessCluster deploys:
    when a snapshot's recorded subtask counts differ from what ``plan``
    will deploy, redistribute it (keyed state AND persisted in-flight
    channel state) through :func:`rescale_snapshot` instead of restoring
    positionally — a positional restore at the wrong parallelism silently
    drops/misroutes whole key-group ranges.  Snapshots matching the plan
    (and non-subtask layouts) pass through untouched."""
    if not isinstance(restore, dict):
        return restore
    counts = None
    mismatch = False
    for v in plan.vertices:
        entry = restore.get(v.uid)
        if not isinstance(entry, dict) or "subtasks" not in entry:
            continue
        if counts is None:
            counts = counts_for_plan(plan)
        if len(entry["subtasks"]) != counts[v.uid]:
            mismatch = True
            break
    if not mismatch:
        return restore
    return rescale_snapshot(restore, plan, counts)


class AdaptiveScheduler:
    """Reactive scheduler over the MiniCluster."""

    def __init__(self, plan_factory: Callable[[int], ExecutionPlan],
                 checkpoint_storage=None, checkpoint_interval_ms: int = 20,
                 restart_strategy: Optional[RestartStrategy] = None,
                 min_slots: int = 1):
        self.plan_factory = plan_factory
        self.checkpoint_storage = checkpoint_storage
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.restart_strategy = restart_strategy or FixedDelayRestartStrategy(2)
        self.min_slots = min_slots
        self.state = SchedulerStates.CREATED
        self._slots = 0
        self._desired_slots = 0
        self._cluster: Optional[MiniCluster] = None
        self._result: Optional[JobResult] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.rescales = 0
        self.error: Optional[str] = None

    # -- resources (reactive declaration) ------------------------------------
    def declare_slots(self, n: int) -> None:
        """Reactive mode: the cluster now has ``n`` slots; the scheduler
        rescales the job to use all of them (FLIP-160)."""
        with self._lock:
            self._desired_slots = n

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "AdaptiveScheduler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="adaptive-scheduler")
        self._thread.start()
        return self

    def cancel(self) -> None:
        self._stop.set()
        if self._cluster is not None:
            self._cluster.cancel()

    def join(self, timeout_s: float = 120.0) -> Optional[JobResult]:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        return self._result

    # -- state machine --------------------------------------------------------
    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception as e:  # noqa: BLE001 — scheduler thread must not die silently
            self.error = f"{type(e).__name__}: {e}"
            self.state = SchedulerStates.FAILED

    def _run_inner(self) -> None:
        self.state = SchedulerStates.WAITING_FOR_RESOURCES
        while not self._stop.is_set():
            with self._lock:
                desired = self._desired_slots
            if desired >= self.min_slots:
                break
            clock.sleep(0.01)
        raw_restore: Optional[Dict[str, Any]] = None
        while not self._stop.is_set():
            with self._lock:
                self._slots = self._desired_slots
            parallelism = max(self.min_slots, self._slots)
            plan = self.plan_factory(parallelism)
            # split the snapshot for the parallelism we ACTUALLY deploy at —
            # desired slots may have moved again since the savepoint was
            # taken, and restoring N-way-split state into M subtasks would
            # silently drop/misroute key-group ranges
            if raw_restore is not None:
                counts = {
                    v.uid: (len(v.chain[0].source.create_splits(parallelism))
                            if v.is_source else parallelism)
                    for v in plan.vertices}
                restore = rescale_snapshot(raw_restore, plan, counts)
            else:
                restore = None
            cluster = MiniCluster(
                checkpoint_storage=self.checkpoint_storage,
                checkpoint_interval_ms=self.checkpoint_interval_ms)
            self._cluster = cluster
            self.state = SchedulerStates.EXECUTING
            done: Dict[str, Any] = {}

            def run_job(pl=plan, cl=cluster, rs=restore):
                done["result"] = cl.execute(pl, restore=rs, timeout_s=600)

            th = threading.Thread(target=run_job, daemon=True)
            th.start()
            rescale_to: Optional[int] = None
            while th.is_alive():
                if self._stop.is_set():
                    cluster.cancel()
                    break
                with self._lock:
                    if self._desired_slots != parallelism and \
                            self._desired_slots >= self.min_slots:
                        rescale_to = self._desired_slots
                if rescale_to is not None:
                    break
                clock.sleep(0.01)
            if rescale_to is not None:
                # take a consistent cut and stop; the split happens at the
                # top of the loop for whatever parallelism wins
                self.state = SchedulerStates.RESTARTING
                sp = cluster.savepoint()
                cluster.cancel()
                th.join(timeout=60)
                raw_restore = (self.checkpoint_storage.load(sp)
                               if sp is not None and self.checkpoint_storage
                               else cluster.latest_restore())
                self.rescales += 1
                continue
            th.join(timeout=60)
            result = done.get("result")
            self._result = result
            if result is None or self._stop.is_set():
                self.state = SchedulerStates.CANCELED
                return
            if result.state == "FINISHED":
                self.state = SchedulerStates.FINISHED
                return
            if result.state == "CANCELED":
                self.state = SchedulerStates.CANCELED
                return
            # failure: consult the restart strategy
            self.restart_strategy.notify_failure()
            if not self.restart_strategy.can_restart():
                self.state = SchedulerStates.FAILED
                return
            self.state = SchedulerStates.RESTARTING
            clock.sleep(self.restart_strategy.delay_ms() / 1000.0)
            raw_restore = (self.checkpoint_storage.load_latest()
                           if self.checkpoint_storage else
                           self._cluster.latest_restore())
        self.state = SchedulerStates.CANCELED


# ---------------------------------------------------------------------------
# reactive autoscaler (ISSUE-14): rescale under fire, no drain
# ---------------------------------------------------------------------------

class AutoscalerPolicy:
    """Hysteresis over the job's backpressure signals -> target parallelism.

    Pure decision logic (unit-testable without a cluster): feed it one
    ``signals`` dict per poll — ``max_queue_depth`` /
    ``alignment_queued_elements`` / ``backpressured_ms_delta`` straight
    off ``MiniCluster.backpressure_totals()``, plus an optional
    ``latency_p99_ms`` from the PR-10 per-(source, hop) histograms — and
    it answers with a new target parallelism or None.

    Hysteresis has three legs, all deliberately boring:

    - **sustain**: a scale decision needs ``sustain_polls`` CONSECUTIVE
      overloaded (resp. underloaded) polls — one deep batch is noise.
    - **dead band**: the scale-out and scale-in thresholds are far apart;
      signals between them reset nothing and decide nothing.
    - **cooldown**: after any decision the policy is silent for
      ``cooldown_ms``, measured through a :class:`MonotoneElapsed` on the
      injectable clock seam — a chaos ``ClockSkew`` backward step cannot
      re-arm an expired cooldown or hold one open forever, so skew cannot
      manufacture a rescale storm.
    """

    def __init__(self, *, min_parallelism: int = 1, max_parallelism: int = 8,
                 scale_factor: int = 2,
                 scale_out_queue_depth: int = 24,
                 scale_in_queue_depth: int = 2,
                 scale_out_alignment_queued: int = 1024,
                 scale_out_backpressured_ms: Optional[float] = None,
                 scale_out_p99_ms: Optional[float] = None,
                 sustain_polls: int = 3, cooldown_ms: float = 2000.0,
                 clock_obj=None):
        if min_parallelism < 1 or max_parallelism < min_parallelism:
            raise ValueError("AutoscalerPolicy: need 1 <= min <= max")
        if scale_factor < 2:
            raise ValueError("AutoscalerPolicy: scale_factor must be >= 2")
        self.min_parallelism = min_parallelism
        self.max_parallelism = max_parallelism
        self.scale_factor = scale_factor
        self.scale_out_queue_depth = scale_out_queue_depth
        self.scale_in_queue_depth = scale_in_queue_depth
        self.scale_out_alignment_queued = scale_out_alignment_queued
        self.scale_out_backpressured_ms = scale_out_backpressured_ms
        self.scale_out_p99_ms = scale_out_p99_ms
        self.sustain_polls = max(1, int(sustain_polls))
        self.cooldown_ms = float(cooldown_ms)
        self._clock = clock_obj
        self._over = 0
        self._under = 0
        self._cooldown: Optional[MonotoneElapsed] = None

    # -- introspection -----------------------------------------------------
    def cooldown_remaining_ms(self) -> float:
        if self._cooldown is None:
            return 0.0
        return max(0.0, self.cooldown_ms - self._cooldown.ms())

    def in_cooldown(self) -> bool:
        return self.cooldown_remaining_ms() > 0.0

    def restart_cooldown(self) -> None:
        """(Re-)arm the cooldown — the autoscaler calls this when a rescale
        actually COMPLETES, so the window measures from redeploy, not from
        the decision."""
        self._cooldown = MonotoneElapsed(self._clock)

    def cancel_cooldown(self) -> None:
        """Disarm the decision-time cooldown: a decided rescale that could
        not execute (no cut possible) must not silence the policy for a
        full cooldown window while the job keeps drowning."""
        self._cooldown = None

    # -- classification ----------------------------------------------------
    def _overloaded(self, s: Dict[str, Any]) -> bool:
        if s.get("max_queue_depth", 0) >= self.scale_out_queue_depth:
            return True
        if s.get("alignment_queued_elements", 0) \
                >= self.scale_out_alignment_queued:
            return True
        bp = self.scale_out_backpressured_ms
        if bp is not None and s.get("backpressured_ms_delta", 0.0) >= bp:
            return True
        p99 = s.get("latency_p99_ms")
        return (self.scale_out_p99_ms is not None and p99 is not None
                and p99 >= self.scale_out_p99_ms)

    def _underloaded(self, s: Dict[str, Any]) -> bool:
        if s.get("max_queue_depth", 0) > self.scale_in_queue_depth:
            return False
        if s.get("alignment_queued_elements", 0) > 0:
            return False
        bp = self.scale_out_backpressured_ms
        if bp is not None and s.get("backpressured_ms_delta", 0.0) > bp / 4:
            return False
        return True

    def observe(self, signals: Dict[str, Any],
                current: int) -> Optional[int]:
        """One poll: returns the new target parallelism, or None.  The
        caller performs the rescale; :meth:`restart_cooldown` re-arms the
        window once the new deployment is live."""
        if self.in_cooldown():
            # signals during cooldown neither decide nor accumulate — the
            # whole point is to let the new deployment's queues settle
            self._over = self._under = 0
            return None
        if self._overloaded(signals):
            self._over += 1
            self._under = 0
            if self._over >= self.sustain_polls \
                    and current < self.max_parallelism:
                self._over = self._under = 0
                self.restart_cooldown()
                return min(self.max_parallelism,
                           current * self.scale_factor)
        elif self._underloaded(signals):
            self._under += 1
            self._over = 0
            if self._under >= self.sustain_polls \
                    and current > self.min_parallelism:
                self._over = self._under = 0
                self.restart_cooldown()
                return max(self.min_parallelism,
                           max(1, current // self.scale_factor))
        else:
            self._over = self._under = 0   # dead band
        return None


class ReactiveAutoscaler:
    """FLIP-160's reactive loop closed over the live backpressure signals:
    run the job, watch its gauges, and rescale it MID-STREAM through an
    unaligned checkpoint — the backpressured job is never drained.

    Rescale lifecycle (each phase an instant on the ``rescale`` trace
    span; the whole window bounded by ``rescale_deadline_ms`` through the
    clock seam):

    1. **trigger** — take a fresh cut of the RUNNING job via
       ``MiniCluster.checkpoint()`` (regular barriers: they escalate to
       unaligned under backpressure, so the cut completes in bounded time
       precisely when the job is drowning).
    2. **checkpoint** — load the cut (the immutable pre-rescale anchor).
    3. **redistribute** — ``rescale_snapshot``: keyed operator state
       re-splits by key-group range and the persisted in-flight channel
       state re-routes by each record's own key.  The
       ``rescale.redistribute`` chaos point fires here; an injected kill
       (``KillDuringRescale``) is absorbed by re-triggering from the same
       cut (idempotent — the cut never mutates), bounded by
       ``rescale_retries`` and the deadline, after which the lifecycle
       ROLLS BACK: redeploy the OLD parallelism from the same cut.
    4. **redeploy** — cancel the old deployment, deploy the new plan with
       the redistributed restore; a worker dying after this point is
       handled by the cluster's own restart strategy, whose restore path
       redistributes the pre-rescale checkpoint again
       (``maybe_rescale_restore``) — same idempotent re-trigger.
    5. **first-output** — the span completes when the new deployment
       processes its first records.

    Exactly-once across all of it: every pre-cut record is either in the
    operator snapshots or in the redistributed channel state (exactly
    once), and every post-cut record replays from the source offsets.
    """

    def __init__(self, plan_factory: Callable[[int], ExecutionPlan],
                 checkpoint_storage=None, *,
                 policy: Optional[AutoscalerPolicy] = None,
                 initial_parallelism: Optional[int] = None,
                 poll_interval_ms: float = 25.0,
                 rescale_deadline_ms: float = 60_000.0,
                 rescale_retries: int = 1,
                 checkpoint_interval_ms: int = 20,
                 alignment_timeout_ms: Optional[float] = 100.0,
                 checkpoint_timeout_s: float = 30.0,
                 restart_attempts: int = 2,
                 channel_capacity: int = 32,
                 job_timeout_s: float = 600.0,
                 latency_interval_ms: Optional[int] = None,
                 incremental: bool = False):
        self.plan_factory = plan_factory
        self.checkpoint_storage = checkpoint_storage
        self.incremental = bool(incremental)
        self.policy = policy or AutoscalerPolicy()
        self.poll_interval_ms = float(poll_interval_ms)
        self.rescale_deadline_ms = float(rescale_deadline_ms)
        self.rescale_retries = int(rescale_retries)
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.alignment_timeout_ms = alignment_timeout_ms
        self.checkpoint_timeout_s = checkpoint_timeout_s
        self.restart_attempts = restart_attempts
        self.channel_capacity = channel_capacity
        self.job_timeout_s = job_timeout_s
        self.latency_interval_ms = latency_interval_ms
        self.state = SchedulerStates.CREATED
        self.error: Optional[str] = None
        self.parallelism = (initial_parallelism
                            if initial_parallelism is not None
                            else self.policy.min_parallelism)
        self.target_parallelism = self.parallelism
        self.parallelism_path: List[int] = [self.parallelism]
        self.rescales = 0
        self.rollbacks = 0
        self.retriggers = 0
        self.rescales_skipped = 0
        self.last_rescale_duration_ms: Optional[float] = None
        self._last_signals: Dict[str, Any] = {}
        self._last_bp_ms = 0.0
        self._cluster: Optional[MiniCluster] = None
        self._result: Optional[JobResult] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReactiveAutoscaler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="reactive-autoscaler")
        self._thread.start()
        return self

    def cancel(self) -> None:
        self._stop.set()
        c = self._cluster
        if c is not None:
            c.cancel()

    def join(self, timeout_s: float = 300.0) -> Optional[JobResult]:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        return self._result

    # -- observability -----------------------------------------------------
    def status(self) -> Dict[str, Any]:
        """``job_status()["autoscaler"]`` / ``autoscaler.*`` gauge view."""
        with self._lock:
            return {
                "state": self.state,
                "current_parallelism": self.parallelism,
                "target_parallelism": self.target_parallelism,
                "min_parallelism": self.policy.min_parallelism,
                "max_parallelism": self.policy.max_parallelism,
                "rescales": self.rescales,
                "rollbacks": self.rollbacks,
                "retriggers": self.retriggers,
                "rescales_skipped": self.rescales_skipped,
                "last_rescale_duration_ms": self.last_rescale_duration_ms,
                "cooldown_remaining_ms": round(
                    self.policy.cooldown_remaining_ms(), 1),
                "parallelism_path": list(self.parallelism_path),
                "signals": dict(self._last_signals),
            }

    def _read_signals(self, cluster: MiniCluster) -> Dict[str, Any]:
        bp = cluster.backpressure_totals()
        total_ms = bp.get("total_backpressured_ms", 0.0)
        delta = max(0.0, total_ms - self._last_bp_ms)
        self._last_bp_ms = total_ms
        p99 = None
        rows = cluster.latency_tracker.panel()
        if rows:
            p99 = max(r.get("p99_ms", 0.0) for r in rows)
        signals = {"max_queue_depth": bp.get("max_queue_depth", 0),
                   "alignment_queued_elements":
                       bp.get("alignment_queued_elements", 0),
                   "backpressured_ms_delta": round(delta, 3),
                   "total_backpressured_ms": total_ms,
                   "latency_p99_ms": p99}
        with self._lock:
            self._last_signals = signals
        return signals

    # -- internals ---------------------------------------------------------
    def _make_cluster(self) -> MiniCluster:
        from flink_tpu.metrics.groups import autoscaler_metrics

        cluster = MiniCluster(
            checkpoint_storage=self.checkpoint_storage,
            checkpoint_interval_ms=self.checkpoint_interval_ms,
            alignment_timeout_ms=self.alignment_timeout_ms,
            checkpoint_timeout_s=self.checkpoint_timeout_s,
            restart_attempts=self.restart_attempts,
            channel_capacity=self.channel_capacity,
            tolerable_failed_checkpoints=-1,
            latency_interval_ms=self.latency_interval_ms,
            incremental=self.incremental)
        cluster.autoscaler_status_supplier = self.status
        autoscaler_metrics(cluster.job_metric_group, self.status)
        # incarnation fencing: the new deployment's checkpoint ids start
        # ABOVE everything previous incarnations stored, so load_latest()
        # can never prefer an abandoned incarnation's checkpoint
        base = getattr(self, "_next_cid_base", 0)
        if base:
            cluster._next_checkpoint_id = base
        return cluster

    def _split_for(self, raw: Dict[str, Any],
                   plan: ExecutionPlan) -> Dict[str, Any]:
        return rescale_snapshot(raw, plan, counts_for_plan(plan))

    def _take_cut(self, cluster: MiniCluster,
                  deadline: MonotoneElapsed) -> Optional[int]:
        """A fresh consistent cut of the running job: regular (escalatable)
        checkpoint — returns its id or None when no cut is possible."""
        budget_s = max(0.5, (self.rescale_deadline_ms - deadline.ms())
                       / 1000.0 / 2.0)
        return cluster.checkpoint(timeout_s=min(budget_s,
                                                self.checkpoint_timeout_s))

    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception as e:  # noqa: BLE001 — scheduler thread must not die silently
            self.error = f"{type(e).__name__}: {e}"
            self.state = SchedulerStates.FAILED

    def _run_inner(self) -> None:
        pending: Optional[Tuple[ExecutionPlan, Optional[Dict[str, Any]]]] \
            = None
        raw_restore: Optional[Dict[str, Any]] = None
        restarts = 0
        while not self._stop.is_set():
            if pending is not None:
                plan, restore = pending
                pending = None
            else:
                plan = self.plan_factory(self.parallelism)
                restore = (self._split_for(raw_restore, plan)
                           if raw_restore is not None else None)
            cluster = self._make_cluster()
            self._cluster = cluster
            self._last_bp_ms = 0.0
            self.state = SchedulerStates.EXECUTING
            done: Dict[str, Any] = {}

            def run_job(pl=plan, cl=cluster, rs=restore):
                done["result"] = cl.execute(pl, restore=rs,
                                            timeout_s=self.job_timeout_s)

            th = threading.Thread(target=run_job, daemon=True)
            th.start()
            span_t0 = getattr(self, "_span_t0", None)
            decision: Optional[int] = None
            while th.is_alive():
                if self._stop.is_set():
                    cluster.cancel()
                    break
                if span_t0 is not None:
                    # first-output detection: the rescale span ends when
                    # the NEW deployment processes records again
                    import time as _time
                    if any(t.records_in > 0
                           for t in getattr(cluster, "_tasks", [])
                           if not hasattr(t, "split")):
                        dur_ns = _time.perf_counter_ns() - span_t0
                        tracing.complete(
                            "rescale", span_t0, _time.perf_counter_ns(),
                            cat="rescale",
                            from_parallelism=self._span_from,
                            to_parallelism=self.parallelism,
                            rolled_back=self._span_rolled_back,
                            retriggers=self.retriggers)
                        with self._lock:
                            self.last_rescale_duration_ms = round(
                                dur_ns / 1e6, 1)
                        self.policy.restart_cooldown()
                        span_t0 = None
                        self._span_t0 = None
                signals = self._read_signals(cluster)
                target = self.policy.observe(signals, self.parallelism)
                if target is not None and target != self.parallelism:
                    attempt = self._rescale(cluster, th, target)
                    if attempt is None:
                        # no cut possible (job finishing / sources done):
                        # the deployment keeps running, monitoring resumes
                        # — and the decision-time cooldown disarms so the
                        # next sustained overload re-attempts promptly
                        self.policy.cancel_cooldown()
                        with self._lock:
                            self.rescales_skipped += 1
                        continue
                    decision = target
                    pending = attempt
                    break
                clock.sleep(self.poll_interval_ms / 1000.0)
            if decision is None:
                th.join(timeout=self.job_timeout_s)
                result = done.get("result")
                self._result = result
                if result is None or self._stop.is_set():
                    self.state = SchedulerStates.CANCELED
                    return
                if result.state == "FINISHED":
                    self.state = SchedulerStates.FINISHED
                    return
                if result.state == "CANCELED":
                    self.state = SchedulerStates.CANCELED
                    return
                # execution failed past the cluster's own restart budget:
                # re-trigger from the newest durable state (idempotent —
                # a worker killed mid-redeploy lands here and redeploys
                # from the same pre-rescale checkpoint)
                if restarts >= self.restart_attempts:
                    self.state = SchedulerStates.FAILED
                    self.error = result.error
                    return
                restarts += 1
                self.state = SchedulerStates.RESTARTING
                raw_restore = (self.checkpoint_storage.load_latest()
                               if self.checkpoint_storage is not None
                               else cluster.latest_restore()) or raw_restore
                continue
            # ---- rescale under fire: the next iteration deploys the
            # already-redistributed (plan, restore) from ``pending``
            raw_restore = self._raw_cut
        self.state = SchedulerStates.CANCELED

    def _rescale(self, cluster: MiniCluster, th: threading.Thread,
                 target: int
                 ) -> Optional[Tuple[ExecutionPlan, Dict[str, Any]]]:
        """Execute one supervised rescale: cut -> cancel -> redistribute
        (retried, chaos-exposed) -> return the (plan, restore) to deploy.
        Rolls back to the old parallelism past the retry/deadline budget.
        Returns None when no cut could be taken (the job keeps running)."""
        import time as _time

        old_p = self.parallelism
        deadline = MonotoneElapsed()
        t0 = _time.perf_counter_ns()
        if getattr(self, "_span_t0", None) is not None:
            # back-to-back rescale decided before the previous
            # deployment's first output: close the previous span now
            # (truncated at this trigger) so its timeline row exists and
            # the new rescale's bookkeeping cannot clobber it
            tracing.complete("rescale", self._span_t0, t0, cat="rescale",
                             from_parallelism=self._span_from,
                             to_parallelism=old_p,
                             rolled_back=self._span_rolled_back,
                             truncated=True)
            with self._lock:
                self.last_rescale_duration_ms = round(
                    (t0 - self._span_t0) / 1e6, 1)
            self._span_t0 = None
        tracing.instant("rescale.trigger", cat="rescale",
                        from_parallelism=old_p, to_parallelism=target)
        with self._lock:
            self.target_parallelism = target
        self.state = SchedulerStates.RESTARTING
        cid = self._take_cut(cluster, deadline)
        if cid is None:
            with self._lock:
                self.target_parallelism = old_p
            self.state = SchedulerStates.EXECUTING
            return None
        tracing.instant("rescale.checkpoint", cat="rescale", checkpoint=cid)
        raw = (self.checkpoint_storage.load(cid)
               if self.checkpoint_storage is not None
               else cluster.latest_restore())
        self._raw_cut = raw
        cluster.cancel()
        th.join(timeout=60)
        while th.is_alive() and deadline.ms() < self.rescale_deadline_ms:
            th.join(timeout=1.0)
        if th.is_alive():
            # the old incarnation refuses to die (a wedged subtask, a
            # stuck chaos stall): deploying the new one on top would run
            # both against the SAME shared sink/operator instances — the
            # exactly-once race the deploy barrier closes, resurrected
            # across incarnations.  Fail LOUDLY instead.
            raise RuntimeError(
                f"rescale {old_p}->{target}: old deployment still alive "
                f"after cancel + {self.rescale_deadline_ms:.0f}ms deadline "
                f"— refusing to deploy a second incarnation over it")
        # incarnation fencing: the OLD deployment's periodic checkpoints
        # may have completed AFTER the cut (higher ids) — they describe an
        # abandoned future the new deployment will re-derive differently.
        # Re-store the cut as the newest id and start the next
        # incarnation's ids above it, so any restart restores the cut (or
        # the new incarnation's own later checkpoints), never an orphan.
        if self.checkpoint_storage is not None:
            last = max(list(cluster._completed_ids) + [cid])
            if last > cid:
                self.checkpoint_storage.store(last + 1, raw)
                self._next_cid_base = last + 2
            else:
                self._next_cid_base = cid + 1
        else:
            self._next_cid_base = cid + 1
        attempts = 0
        new_p = target
        rolled_back = False
        while True:
            try:
                plan = self.plan_factory(new_p)
                restore = self._split_for(raw, plan)
                tracing.instant("rescale.redistribute", cat="rescale",
                                to_parallelism=new_p)
                # redeploy fault point: deterministic deploy-step failures
                chaos.fire("rescale.redeploy", to_parallelism=new_p)
                tracing.instant("rescale.redeploy", cat="rescale",
                                to_parallelism=new_p)
                break
            except Exception as e:  # noqa: BLE001 — the rescale window is a failure domain
                if not rolled_back and attempts < self.rescale_retries \
                        and deadline.ms() < self.rescale_deadline_ms:
                    # idempotent re-trigger: the cut is immutable, so the
                    # redistribution simply runs again
                    attempts += 1
                    with self._lock:
                        self.retriggers += 1
                    continue
                if rolled_back:
                    # even the rollback deploy failed: surface it
                    raise
                rolled_back = True
                with self._lock:
                    self.rollbacks += 1
                    self.target_parallelism = old_p
                new_p = old_p
                self.error = (f"rescale {old_p}->{target} rolled back: "
                              f"{type(e).__name__}: {e}")
        with self._lock:
            self.parallelism = new_p
            self.target_parallelism = new_p
            if not rolled_back:
                self.rescales += 1
            self.parallelism_path.append(new_p)
        self._span_t0 = t0
        self._span_from = old_p
        self._span_rolled_back = rolled_back
        return plan, restore
