"""Heartbeat manager: liveness monitoring between coordinators.

Analog of ``runtime/heartbeat/HeartbeatManagerImpl.java:43``: a *sender* side
periodically requests heartbeats from monitored targets; each target's last
response is timestamped; a target silent past the timeout triggers the
listener's ``notify_heartbeat_timeout`` — the failure-detection signal that
drives failover (SURVEY §5.3).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from flink_tpu.testing import chaos


class HeartbeatTarget:
    """What the monitor pings (``HeartbeatTarget`` analog): any callable that
    requests a heartbeat from the remote side; the remote side answers by
    calling ``receive_heartbeat``."""

    def __init__(self, request_fn: Callable[[], None]):
        self.request_fn = request_fn


class HeartbeatMonitor:
    __slots__ = ("target", "last_heartbeat")

    def __init__(self, target: HeartbeatTarget, now: float):
        self.target = target
        self.last_heartbeat = now


def _monotonic() -> float:
    """Default liveness clock: the injectable seam (a chaos ``ClockSkew``
    on ``clock.monotonic`` can falsely age heartbeats — the
    local-clock-jump false suspect, distinct from the dropped-delivery
    partition)."""
    from flink_tpu.utils.clock import monotonic
    return monotonic()


class HeartbeatManager:
    def __init__(self, interval_s: float = 0.2, timeout_s: float = 1.0,
                 on_timeout: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = _monotonic):
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.on_timeout = on_timeout
        self._clock = clock
        self._monitors: Dict[str, HeartbeatMonitor] = {}
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._stopped = False

    def start(self) -> None:
        self._schedule()

    def monitor_target(self, resource_id: str, target: HeartbeatTarget) -> None:
        with self._lock:
            self._monitors[resource_id] = HeartbeatMonitor(target, self._clock())

    def unmonitor_target(self, resource_id: str) -> None:
        with self._lock:
            self._monitors.pop(resource_id, None)

    def receive_heartbeat(self, resource_id: str) -> None:
        # fault point: a partitioned target's heartbeats are dropped on the
        # floor (the monitor never sees them -> timeout fires even though
        # the target is alive — the classic one-way partition false
        # suspect).  direction="response" pairs with the request-side
        # firing in _tick: a Partition(direction=...) drops exactly one
        # of the two (the ASYMMETRIC partition); an undirected Partition
        # drops both.
        if not chaos.fire("heartbeat.deliver", target=resource_id,
                          direction="response"):
            return
        with self._lock:
            m = self._monitors.get(resource_id)
            if m is not None:
                m.last_heartbeat = self._clock()

    def _schedule(self) -> None:
        if self._stopped:
            return
        self._timer = threading.Timer(self.interval_s, self._tick)
        self._timer.daemon = True
        self._timer.start()

    def _tick(self) -> None:
        now = self._clock()
        with self._lock:
            items = list(self._monitors.items())
        dead = []
        for rid, m in items:
            if now - m.last_heartbeat > self.timeout_s:
                dead.append(rid)
            else:
                # fault point, request direction: the monitor's heartbeat
                # REQUEST can be partitioned away independently of the
                # target's response (direction="request")
                if not chaos.fire("heartbeat.deliver", target=rid,
                                  direction="request"):
                    continue
                try:
                    m.target.request_fn()
                except Exception:  # target unreachable → let timeout fire
                    pass
        for rid in dead:
            self.unmonitor_target(rid)
            if self.on_timeout is not None:
                self.on_timeout(rid)
        self._schedule()

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
