"""MiniCluster: multi-subtask parallel job execution in one process.

Analog of the reference's ``MiniCluster.java`` (Dispatcher + JobMaster +
TaskExecutors in one JVM with real RPC/network/checkpointing): deploys an
``ExecutionPlan`` with REAL parallelism — one thread per subtask, bounded
channels between them (credit-style backpressure), hash/rebalance/broadcast
partitioners on the edges — plus a **CheckpointCoordinator**
(``CheckpointCoordinator.java:96``): periodic triggers to source subtasks,
in-band barriers (aligned or unaligned), ack collection, completed-checkpoint
store and ``notifyCheckpointComplete`` fan-out, and failure recovery by
restarting the job from the latest completed checkpoint
(restart-strategy analog, full-restart region).

Checkpoint layout: ``{uid: {"subtasks": [per-subtask snapshot, ...]}}`` plus
``__job__`` metadata.  On restore with the same parallelism each subtask gets
its own snapshot back; sources replay from their recorded offsets.

NOTE on devices: subtasks are threads, and concurrent jit dispatch from many
threads onto ONE physical TPU chip can crash the device client — run the
MiniCluster on the CPU platform (tests do: ``jax_platforms=cpu``) or give
each subtask its own device; single-chip TPU work belongs on the
single-threaded LocalExecutor / the sharded ``parallel`` path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.cluster.channels import LocalChannel, OutputDispatcher
from flink_tpu.cluster.task import (SourceSubtask, Subtask, SubtaskBase,
                                    TaskListener, TaskStates)
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.graph.stream_graph import ExecutionPlan, PlanVertex
from flink_tpu.observability import tracing
from flink_tpu.utils import clock


@dataclass
class _PendingCheckpoint:
    checkpoint_id: int
    expected: int
    #: monotone elapsed timer (injectable clock seam): expiry decisions
    #: never regress under a chaos ClockSkew backward step
    timer: "clock.MonotoneElapsed"
    #: trigger-time perf reading — the trigger→complete span endpoints
    t0_ns: int = 0
    acks: Dict[Tuple[str, int], Dict[str, Any]] = field(default_factory=dict)
    #: OperatorCoordinator snapshots taken at TRIGGER time (the reference
    #: snapshots SourceCoordinator state before triggering tasks, §3.4)
    enumerators: Optional[Dict[str, Any]] = None


def _vertex_watermark(tasks) -> Optional[int]:
    """Min current watermark across a vertex's subtasks (the per-vertex
    ``currentInputWatermark`` metric the reference UI shows), or None
    before any watermark arrived."""
    from flink_tpu.core.batch import LONG_MIN

    wms = []
    for t in tasks:
        valve = getattr(t, "_valve", None)
        if valve is not None:
            wms.append(valve.current)
        else:
            op_wm = getattr(t.operator, "watermark", None)
            if isinstance(op_wm, int):
                wms.append(op_wm)
    if not wms or any(w == LONG_MIN for w in wms):
        return None                     # not established vertex-wide yet
    return min(wms)


def _state_size(tree) -> int:
    """Approximate serialized checkpoint size: array nbytes + byte-string
    lengths through the nested snapshot (cheap — no re-pickling)."""
    import numpy as np

    if isinstance(tree, dict):
        return sum(_state_size(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(_state_size(v) for v in tree)
    if isinstance(tree, np.ndarray):
        return int(tree.nbytes)
    if isinstance(tree, (bytes, bytearray)):
        return len(tree)
    return 8


@dataclass
class JobResult:
    job_name: str
    state: str                      # FINISHED / FAILED / CANCELED
    net_runtime_ms: float
    restarts: int = 0
    completed_checkpoints: List[int] = field(default_factory=list)
    error: Optional[str] = None


class MiniCluster(TaskListener):
    #: synthetic "vertex" charged with checkpoint-policy failures: never in
    #: any plan, so region lookup falls back to a FULL restart
    _CHECKPOINT_COORDINATOR_UID = "__checkpoint_coordinator__"

    def __init__(self, checkpoint_storage=None, checkpoint_interval_ms: int = 0,
                 unaligned: bool = False, checkpoint_timeout_s: float = 60.0,
                 restart_attempts: int = 0, restart_delay_ms: int = 50,
                 channel_capacity: int = 32, restart_strategy=None,
                 config=None, tolerable_failed_checkpoints: int = 0,
                 alignment_timeout_ms: Optional[float] = None,
                 alignment_queue_max: Optional[int] = None,
                 latency_interval_ms: Optional[int] = None,
                 tracing_enabled: Optional[bool] = None,
                 queryable_replicas: int = 1,
                 incremental: bool = False):
        from flink_tpu.cluster.failover import (FixedDelayRestartStrategy,
                                                NoRestartStrategy)
        from flink_tpu.config.options import (CheckpointingOptions,
                                              MetricOptions, StateOptions)
        from flink_tpu.observability import LatencyTracker
        from flink_tpu.observability import tracing as tracing_mod
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureManager

        self.config = config
        # unaligned-checkpoint policy: explicit args win, then config keys,
        # then the option defaults (aligned, 8192-element queue cap)
        if config is not None:
            if not unaligned:
                unaligned = bool(config.get(CheckpointingOptions.UNALIGNED))
            if alignment_timeout_ms is None:
                alignment_timeout_ms = config.get(
                    CheckpointingOptions.ALIGNMENT_TIMEOUT)
        # latency tracking + tracing: explicit args win, then the
        # metrics.latency.interval / metrics.tracing.* config keys
        if latency_interval_ms is None and config is not None:
            latency_interval_ms = config.get(MetricOptions.LATENCY_INTERVAL)
        self.latency_interval_ms = int(latency_interval_ms or 0)
        if tracing_enabled is None and config is not None:
            tracing_enabled = bool(config.get(MetricOptions.TRACING_ENABLED))
        self.tracing_enabled = bool(tracing_enabled)
        #: THIS cluster's journal handle: job_status()/trace_events() read
        #: it instead of the process singleton, so a tracing-off job in
        #: the same process never reports another job's spans as its own
        self._trace_journal = None
        #: True only when THIS cluster installed the journal: an adopted
        #: pre-existing journal belongs to whoever installed it (a bench
        #: harness, an outer job) — we record into it but never reset()
        #: it, and its owner's capacity choice wins over config
        self._owns_trace_journal = False
        if self.tracing_enabled:
            cap = (config.get(MetricOptions.TRACING_BUFFER)
                   if config is not None
                   else MetricOptions.TRACING_BUFFER.default)
            self._trace_journal, self._owns_trace_journal = \
                tracing_mod.adopt_or_install(cap)
        #: per-(source, operator-hop) latency histograms fed by the
        #: LatencyMarker flow; bound to the job metric group below so
        #: every reporter (Prometheus summaries included) exports them
        self.latency_tracker = LatencyTracker()
        if alignment_queue_max is None:
            alignment_queue_max = (
                config.get(CheckpointingOptions.ALIGNMENT_QUEUE_MAX)
                if config is not None
                else CheckpointingOptions.ALIGNMENT_QUEUE_MAX.default)
        self.alignment_timeout_ms = alignment_timeout_ms
        self.alignment_queue_max = alignment_queue_max
        #: queryable serving tier: N-replica read fan-out per state
        #: (reads load-balance across the freshest members; a partitioned
        #: member's traffic fails over to a sibling)
        self.queryable_replicas = max(1, int(queryable_replicas))
        #: last completed checkpoint's alignment accounting (job_status()
        #: ["checkpoints"] + the lastCheckpoint* gauges)
        self._last_alignment: Dict[str, Any] = {
            "last_alignment_duration_ms": 0.0, "last_overtaken_bytes": 0,
            "last_persisted_inflight_bytes": 0, "unaligned_checkpoints": 0}
        #: execution.checkpointing.tolerable-failed-checkpoints analog:
        #: declined/timed-out/storage-failed checkpoints beyond this many
        #: CONSECUTIVE failures trigger job failover (-1 = unlimited)
        self.failure_manager = CheckpointFailureManager(
            tolerable_failed_checkpoints)
        self.checkpoint_storage = checkpoint_storage
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.unaligned = unaligned
        # incremental (delta) checkpoints: explicit arg wins, then the
        # state.backend.incremental config key
        if not incremental and config is not None:
            incremental = bool(config.get(StateOptions.INCREMENTAL))
        self.incremental = bool(incremental)
        self.incremental_rebase_ratio = float(
            config.get(CheckpointingOptions.INCREMENTAL_REBASE_RATIO)
            if config is not None
            else CheckpointingOptions.INCREMENTAL_REBASE_RATIO.default)
        self.changelog_materialization_threshold = int(
            config.get(StateOptions.CHANGELOG_MATERIALIZATION_THRESHOLD)
            if config is not None
            else StateOptions.CHANGELOG_MATERIALIZATION_THRESHOLD.default)
        self.checkpoint_timeout_s = checkpoint_timeout_s
        self.restart_attempts = restart_attempts
        self.restart_delay_ms = restart_delay_ms
        self.channel_capacity = channel_capacity
        #: pluggable restart policy (fixed/exponential/failure-rate);
        #: restart_attempts kept as the back-compat shorthand
        self.restart_strategy = restart_strategy or (
            FixedDelayRestartStrategy(restart_attempts, restart_delay_ms)
            if restart_attempts > 0 else NoRestartStrategy())
        self._lock = threading.Lock()
        self._tasks: List[SubtaskBase] = []
        self._slot_memory_pool = None  # lazy: SlotMemoryPool
        self._pending: Optional[_PendingCheckpoint] = None
        self._completed_ids: List[int] = []
        self._next_checkpoint_id = 1
        self._failed: Optional[str] = None
        self._stop_requested = False
        # pre-deploy defaults: REST calls may land before execute()
        self._finished: set = set()
        self._source_tasks: List[SourceSubtask] = []
        self._subtask_counts: Dict[str, int] = {}
        #: per-checkpoint stats (CheckpointStatsTracker analog) — id,
        #: duration, state size; surfaced by REST + the dashboard
        self._checkpoint_stats: List[Dict[str, Any]] = []
        #: every task failure ever seen (JobExceptionsHandler's history,
        #: not just the current root cause); bounded
        self._exception_history: List[Dict[str, Any]] = []
        #: restarts performed by the CURRENT/most recent execute() —
        #: surfaced by job_status() next to the failed-checkpoint counters
        self._restarts = 0
        #: job-scope metric group: numberOfCompleted/FailedCheckpoints +
        #: numRestarts (CheckpointStatsTracker analogs) on a jobmanager
        #: root, so reporters attached to ``metrics_registry`` export them
        from flink_tpu.metrics.groups import (MetricRegistry,
                                              backpressure_metrics,
                                              checkpoint_alignment_metrics,
                                              device_health_metrics,
                                              job_checkpoint_metrics)
        self.metrics_registry = MetricRegistry()
        self.job_metric_group = job_checkpoint_metrics(
            self.metrics_registry.job_manager_group(), self.failure_manager,
            lambda: self._restarts)
        #: device-lane health gauges (runtime/device_health.py): the
        #: process-wide monitor's state + this job's degraded operators
        device_health_metrics(self.job_metric_group,
                              self.device_health_status)
        #: channel backpressure + unaligned-checkpoint alignment gauges
        backpressure_metrics(self.job_metric_group, self.backpressure_totals)
        checkpoint_alignment_metrics(self.job_metric_group,
                                     lambda: self._last_alignment)
        #: latency.* histogram + p50/p99 gauge export rides the same group
        self.latency_tracker.bind_group(self.job_metric_group)
        #: queryable serving tier (ISSUE-9): auto-wired at deploy when any
        #: operator was built with ``queryable=<name>`` — live views per
        #: subtask + a checkpoint replica fed from _complete_checkpoint
        self.queryable = None
        #: reactive-autoscaler status supplier (cluster/adaptive.py
        #: ReactiveAutoscaler attaches it to each cluster it deploys):
        #: surfaces as ``job_status()["autoscaler"]`` + autoscaler.* gauges
        self.autoscaler_status_supplier = None
        #: coordinator HA (ISSUE-20): optional callable(checkpoint_id) ->
        #: bool consulted BEFORE a completed checkpoint is stored/notified
        #: — the leader-epoch fence (e.g. FileHaStore pointer advance).
        #: False/raise = this coordinator is a zombie ex-leader: the
        #: completion aborts (no store, no notify, so 2PC never commits)
        #: and the failure budget is charged
        self.ha_commit_gate = None
        #: completions this cluster lost to the HA fence
        self.ha_fenced_completions = 0
        #: HA panel supplier: surfaces as ``job_status()["ha"]`` + the
        #: ``/jobs/<id>/ha`` REST endpoint
        self.ha_status_supplier = None
        from flink_tpu.metrics.groups import ha_metrics

        def _ha_status():
            if self.ha_status_supplier is None:
                return None
            try:
                return self.ha_status_supplier()
            except Exception:  # noqa: BLE001 — gauges never raise
                return None
        ha_metrics(self.job_metric_group, _ha_status)

    # ------------------------------------------------------------ listener
    def _slot_memory(self):
        """The next slot's managed-memory accountant (round-robin over the
        executor's fixed slot pool — TaskManagerOptions sizing; restarts
        REUSE slots, so aggregate managed memory stays bounded)."""
        from flink_tpu.runtime.memory import SlotMemoryPool

        if self._slot_memory_pool is None:
            self._slot_memory_pool = SlotMemoryPool(self.config)
        return self._slot_memory_pool.assign()

    def task_state_changed(self, vertex_uid: str, subtask_index: int,
                           state: str, error: Optional[str]) -> None:
        if state == TaskStates.FAILED:
            with self._lock:
                if self._failed is None:
                    self._failed = f"{vertex_uid}[{subtask_index}]: {error}"
                self._exception_history.append({
                    "timestamp_ms": int(time.time() * 1000),
                    "task": f"{vertex_uid}[{subtask_index}]",
                    "exception": str(error)})
                del self._exception_history[:-50]   # bounded history
        elif state == TaskStates.FINISHED:
            with self._lock:
                self._finished.add((vertex_uid, subtask_index))
                # a task finishing mid-alignment will never ack: shrink the
                # expectation so the checkpoint can still complete
                p = self._pending
                if p is not None and (vertex_uid, subtask_index) not in p.acks:
                    p.expected -= 1
                    if len(p.acks) >= p.expected:
                        # claims self._pending; a NEW checkpoint may start
                        # during its unlocked store, so don't clear after
                        self._complete_checkpoint(p)

    def acknowledge_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                               subtask_index: int,
                               snapshot: Dict[str, Any]) -> None:
        with self._lock:
            p = self._pending
            if p is None or p.checkpoint_id != checkpoint_id:
                return  # late ack for an aborted checkpoint: decline
            # instant AFTER the validity check: a declined late ack must
            # not show up on the timeline as a real lifecycle event (the
            # trigger→complete span's acked count and the ack instants
            # would disagree)
            tracing.instant("checkpoint.ack", cat="checkpoint",
                            checkpoint=checkpoint_id, task=vertex_uid,
                            subtask=subtask_index)
            p.acks[(vertex_uid, subtask_index)] = snapshot
            if len(p.acks) >= p.expected:
                self._complete_checkpoint(p)

    def decline_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                           subtask_index: int, error: str) -> None:
        """A subtask could not snapshot: abort the pending checkpoint and
        charge the failure budget (``receiveDeclineMessage`` analog)."""
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureReason

        with self._lock:
            p = self._pending
            if p is None or p.checkpoint_id != checkpoint_id:
                return                       # already aborted/completed
            self._pending = None
            self._record_checkpoint_failure(
                CheckpointFailureReason.DECLINED, checkpoint_id,
                f"{vertex_uid}[{subtask_index}] declined: {error}")

    def _record_checkpoint_failure(self, reason: str, checkpoint_id: int,
                                   detail: str) -> None:
        """Caller holds ``_lock``.  Counts one in-flight checkpoint failure;
        past the tolerable budget the JOB fails over (the execute loop's
        restart strategy takes it from there, full-restart region)."""
        exceeded = self.failure_manager.on_checkpoint_failure(
            reason, checkpoint_id)
        self._exception_history.append({
            "timestamp_ms": int(time.time() * 1000),
            "task": f"checkpoint-{checkpoint_id}",
            "exception": f"checkpoint {reason}: {detail}"})
        del self._exception_history[:-50]
        if exceeded and self._failed is None:
            self._failed = (
                f"{self._CHECKPOINT_COORDINATOR_UID}[0]: tolerable failed "
                f"checkpoints ({self.failure_manager.tolerable}) exceeded — "
                f"checkpoint {checkpoint_id} {reason}: {detail}")

    def _complete_checkpoint(self, p: _PendingCheckpoint) -> None:
        assembled: Dict[str, Any] = {"__job__": {
            "checkpoint_id": p.checkpoint_id,
            "parallelism": {uid: n for uid, n in self._subtask_counts.items()},
        }}
        if p.enumerators:
            assembled["__enumerators__"] = p.enumerators
        for (uid, idx), snap in p.acks.items():
            entry = assembled.setdefault(
                uid, {"subtasks": [None] * self._subtask_counts[uid]})
            entry["subtasks"][idx] = snap
        # finished tasks no longer ack: carry their FINAL snapshots so the
        # checkpoint stays a complete consistent cut (FLIP-147 analog)
        for t in self._tasks:
            key = (t.vertex_uid, t.subtask_index)
            if key in self._finished and key not in p.acks:
                final = getattr(t, "final_snapshot", None)
                if final is not None:
                    entry = assembled.setdefault(
                        t.vertex_uid,
                        {"subtasks": [None] * self._subtask_counts[t.vertex_uid]})
                    entry["subtasks"][t.subtask_index] = final
        # claim completion BEFORE dropping the lock for storage I/O: late
        # acks/declines for this id are ignored and a new trigger may start
        self._pending = None
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureReason
        # coordinator HA (ISSUE-20): the leader-epoch fence — a zombie
        # ex-leader's completion must abort here, BEFORE bytes land and
        # before any notify fans out (so its 2PC epochs never commit)
        if self.ha_commit_gate is not None:
            try:
                admitted = bool(self.ha_commit_gate(p.checkpoint_id))
            except Exception as e:  # noqa: BLE001 — fence errors = fenced
                admitted = False
                fence_detail = f"{type(e).__name__}: {e}"
            else:
                fence_detail = "stale leader epoch"
            if not admitted:
                self.ha_fenced_completions += 1
                self._record_checkpoint_failure(
                    CheckpointFailureReason.STORAGE, p.checkpoint_id,
                    f"fenced by HA commit gate: {fence_detail}")
                return
        # incremental checkpoints: delta-tracking operators acked increment
        # nodes — resolve them against the previous completed checkpoint's
        # RESOLVED tree so everything downstream (queryable replicas,
        # rescale, in-memory restore) keeps consuming the dense interchange
        # format.  Increment-capable storage persists the RAW tree (bytes
        # scale with the change rate); every other storage gets the
        # self-contained resolved cut.
        from flink_tpu.runtime.checkpoint import delta
        has_delta = delta.tree_has_increment(assembled)
        if has_delta:
            try:
                resolved = delta.apply_increments(
                    getattr(self, "_latest_snapshot", None), assembled)
            except delta.IncrementChainError as e:
                self._record_checkpoint_failure(
                    CheckpointFailureReason.STORAGE, p.checkpoint_id,
                    f"IncrementChainError: {e}")
                return
        else:
            resolved = assembled
        if self.checkpoint_storage is not None:
            store_tree = assembled if (has_delta and getattr(
                self.checkpoint_storage, "supports_increments", False)) \
                else resolved
            # the store (and any retry/backoff wrapper around it) must not
            # stall the coordinator lock: acks, declines and triggers keep
            # flowing while the bytes land
            self._lock.release()
            try:
                try:
                    self.checkpoint_storage.store(p.checkpoint_id, store_tree)
                except Exception as e:  # noqa: BLE001
                    store_error = f"{type(e).__name__}: {e}"
                else:
                    store_error = None
            finally:
                self._lock.acquire()
            if store_error is not None:
                # a storage flake must not kill the ACKING TASK's thread
                # (store runs on it): the checkpoint is abandoned, the
                # failure budget charged, the job keeps running — or fails
                # over once the budget is exhausted
                self._record_checkpoint_failure(
                    CheckpointFailureReason.STORAGE, p.checkpoint_id,
                    store_error)
                return
        self.failure_manager.on_checkpoint_success(p.checkpoint_id)
        self._completed_ids.append(p.checkpoint_id)
        self._latest_snapshot = resolved
        if self.queryable is not None:
            # feed the read replicas off the checkpoint stream: enqueue
            # only (the replica's own ingest thread parses the snapshot —
            # the acking task thread never does serving-tier work)
            self.queryable.on_checkpoint_complete(p.checkpoint_id, resolved)
        # aggregate the subtasks' channel-state (v1) alignment accounting
        # (one shared reader of the schema: task.aggregate_channel_state)
        from flink_tpu.cluster.task import aggregate_channel_state
        agg = aggregate_channel_state(p.acks.values())
        self._last_alignment = {
            "last_alignment_duration_ms": agg["alignment_ms"],
            "last_overtaken_bytes": agg["overtaken_bytes"],
            "last_persisted_inflight_bytes":
                agg["persisted_inflight_bytes"],
            "unaligned_checkpoints":
                self._last_alignment.get("unaligned_checkpoints", 0)
                + int(agg["unaligned"])}
        size = _state_size(resolved)
        # trigger→complete span: the whole lifecycle on one timeline row
        if p.t0_ns:
            tracing.complete("checkpoint", p.t0_ns, time.perf_counter_ns(),
                             cat="checkpoint", checkpoint=p.checkpoint_id,
                             state_size_bytes=size, acked=len(p.acks),
                             unaligned=bool(agg["unaligned"]))
        self._checkpoint_stats.append({
            "id": p.checkpoint_id,
            "completed_at_ms": int(time.time() * 1000),
            "duration_ms": round(p.timer.ms(), 1),
            "state_size_bytes": size,
            # full-vs-delta accounting: what was acked/persisted this cut
            # (== state_size_bytes for a full cut)
            "incremental": has_delta,
            "delta_bytes": _state_size(assembled) if has_delta else size,
            "acked_subtasks": len(p.acks),
            **agg})
        del self._checkpoint_stats[:-100]           # bounded history
        for t in self._tasks:
            t.commands.put(("notify_complete", p.checkpoint_id))

    # ------------------------------------------------------------ deploy
    def _deploy(self, plan: ExecutionPlan,
                restore: Optional[Dict[str, Any]],
                _keep_tasks: Optional[List[SubtaskBase]] = None) -> None:
        self._tasks = list(_keep_tasks or [])
        if _keep_tasks is None:
            self._failed = None
            self._pending = None
            self._finished = set()
        source_tasks: List[SourceSubtask] = [
            t for t in self._tasks if isinstance(t, SourceSubtask)]
        subtask_counts: Dict[str, int] = {}
        # source parallelism = split count (one SourceSubtask per split),
        # EXCEPT runtime-enumerated sources (FLIP-27 coordination): fixed
        # reader count, splits assigned on request by the coordinator
        from flink_tpu.connectors.enumerator import SourceCoordinator
        if _keep_tasks is None or not hasattr(self, "_source_coordinator"):
            self._source_coordinator = SourceCoordinator()
        splits_by_vertex: Dict[int, list] = {}
        dynamic_sources: set = set()
        for v in plan.vertices:
            if v.is_source:
                src = v.chain[0].source
                enum_factory = getattr(src, "create_enumerator", None)
                if enum_factory is not None:
                    dynamic_sources.add(v.id)
                    # region restart (_keep_tasks) keeps the LIVE enumerator
                    # — its assigned-set must survive; only a fresh deploy
                    # (full restart restores it from the checkpoint) builds
                    # a new one
                    if _keep_tasks is None or \
                            v.uid not in self._source_coordinator._enums:
                        self._source_coordinator.register(v.uid,
                                                          enum_factory())
                    subtask_counts[v.uid] = v.parallelism
                    continue
                splits = src.create_splits(v.parallelism)
                splits_by_vertex[v.id] = splits
                subtask_counts[v.uid] = max(1, len(splits))
            else:
                subtask_counts[v.uid] = v.parallelism
        if _keep_tasks is None:
            self._subtask_counts = subtask_counts
        else:
            self._subtask_counts.update(subtask_counts)

        def n_subs(v: PlanVertex) -> int:
            return subtask_counts[v.uid]

        # channels per edge: producer subtask x consumer subtask
        inputs: Dict[int, List[List[LocalChannel]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}
        input_logical: Dict[int, List[List[int]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}
        #: per-input-channel routing metadata (key column / partitioning /
        #: producer max-parallelism / logical port): Subtasks write it
        #: into the v2 channel-state section so persisted in-flight
        #: elements can be re-routed BY KEY on a rescale restore
        input_routing: Dict[int, List[List[Dict[str, Any]]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}

        def edge_routing(e, v) -> Dict[str, Any]:
            return {"partitioning": e.partitioning,
                    "key_column": e.key_column,
                    "max_parallelism": v.max_parallelism,
                    "logical": e.input_index}

        outputs: Dict[int, List[List[OutputDispatcher]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}
        for v in plan.vertices:
            for e in v.out_edges:
                tgt = plan.by_id[e.target_id]
                np_, nc = n_subs(v), n_subs(tgt)
                for pi in range(np_):
                    part = e.partitioning
                    if part == "forward" and np_ == nc:
                        # FORWARD keeps subtask alignment (producer i ->
                        # consumer i): an upstream hash edge's key
                        # partitioning must survive unchained stateful
                        # consumers — rebalancing here would scatter keys
                        ch = LocalChannel(
                            self.channel_capacity,
                            name=f"{v.name}[{pi}]->{tgt.name}[{pi}]")
                        inputs[tgt.id][pi].append(ch)
                        input_logical[tgt.id][pi].append(e.input_index)
                        input_routing[tgt.id][pi].append(edge_routing(e, v))
                        outputs[v.id][pi].append(OutputDispatcher(
                            part, [ch], max_parallelism=v.max_parallelism,
                            subtask_index=pi, key_column=e.key_column))
                        continue
                    chans = [LocalChannel(self.channel_capacity,
                                          name=f"{v.name}[{pi}]->{tgt.name}[{ci}]")
                             for ci in range(nc)]
                    for ci, ch in enumerate(chans):
                        inputs[tgt.id][ci].append(ch)
                        input_logical[tgt.id][ci].append(e.input_index)
                        input_routing[tgt.id][ci].append(edge_routing(e, v))
                    # forward edges with MISMATCHED parallelism degrade to
                    # round-robin (the reference inserts rescale here)
                    if part == "forward" and nc > 1:
                        part = "rebalance"
                    outputs[v.id][pi].append(OutputDispatcher(
                        part, chans, max_parallelism=v.max_parallelism,
                        subtask_index=pi, key_column=e.key_column))

        # deploy barrier: no subtask of THIS deployment processes input
        # before every subtask finished open+restore (shared-instance sink
        # restores REPLACE rows — a sibling's pre-restore fire would be
        # wiped; rescale redeploys hit exactly that race).  Sized to the
        # tasks actually started below; kept-task region restarts gate
        # only the restarted region's tasks.
        n_new = sum(len(splits_by_vertex[v.id])
                    if v.is_source and v.id in splits_by_vertex
                    else subtask_counts[v.uid] for v in plan.vertices)
        self._deploy_gate = threading.Barrier(n_new) if n_new > 1 else None

        restore = restore or {}
        for v in plan.vertices:
            uid = v.uid
            vr = restore.get(uid, {})
            sub_snaps = vr.get("subtasks", [])
            if v.is_source:
                if v.id in dynamic_sources:
                    # runtime coordination: restore the enumerator, then
                    # reclaim every reader-owned in-flight split
                    enum_restore = (restore.get("__enumerators__") or {}) \
                        .get(uid)
                    coord = self._source_coordinator
                    if enum_restore is not None:
                        coord._enums[uid].restore_state(enum_restore)
                    for s in sub_snaps:
                        if not s:
                            continue
                        if s.get("current_split") is not None:
                            coord._enums[uid].reclaim(s["current_split"])
                        for fs in s.get("finished_splits", []):
                            coord._enums[uid].reclaim(fs)
                    for i in range(n_subs(v)):
                        ctx = RuntimeContext(
                            task_name=v.name, subtask_index=i,
                            parallelism=n_subs(v),
                            max_parallelism=v.max_parallelism,
                            memory_manager=self._slot_memory())
                        requester = (lambda u=uid, ri=i:
                                     coord.request_split(u, ri))
                        t = SourceSubtask(uid, i, v.build_operator(),
                                          outputs[v.id][i], ctx, self, None,
                                          split_requester=requester)
                        self._attach_observability(t)
                        t.start(sub_snaps[i] if i < len(sub_snaps) else None)
                        self._tasks.append(t)
                        source_tasks.append(t)
                    continue
                splits = splits_by_vertex[v.id]
                for i, split in enumerate(splits):
                    ctx = RuntimeContext(task_name=v.name, subtask_index=i,
                                         parallelism=len(splits),
                                         max_parallelism=v.max_parallelism,
                                         memory_manager=self._slot_memory())
                    t = SourceSubtask(uid, i, v.build_operator(),
                                      outputs[v.id][i], ctx, self, split)
                    self._attach_observability(t)
                    t.start(sub_snaps[i] if i < len(sub_snaps) else None)
                    self._tasks.append(t)
                    source_tasks.append(t)
            else:
                for i in range(n_subs(v)):
                    ctx = RuntimeContext(task_name=v.name, subtask_index=i,
                                         parallelism=n_subs(v),
                                         max_parallelism=v.max_parallelism,
                                         memory_manager=self._slot_memory())
                    t = Subtask(uid, i, v.build_operator(), outputs[v.id][i],
                                ctx, self, inputs[v.id][i],
                                unaligned=self.unaligned,
                                input_logical=input_logical[v.id][i],
                                alignment_timeout_ms=self.alignment_timeout_ms,
                                alignment_queue_max=self.alignment_queue_max,
                                input_routing=input_routing[v.id][i])
                    self._attach_observability(t)
                    t.start(sub_snaps[i] if i < len(sub_snaps) else None)
                    self._tasks.append(t)
        self._source_tasks = source_tasks
        # job-scope paging occupancy gauges (idempotent registration): only
        # when a deployed operator actually pages device state
        if any(self._iter_paged_operators()):
            from flink_tpu.metrics.groups import paging_metrics
            paging_metrics(self.job_metric_group, self.paging_totals)
        self._wire_queryable(plan)

    def _attach_observability(self, t: SubtaskBase) -> None:
        """Wire latency tracking + the deploy barrier into a subtask
        BEFORE it starts: every hop records markers into the shared
        tracker, sources get the ``metrics.latency.interval`` emission
        cadence, and no subtask processes input until the whole
        deployment finished restoring."""
        t.latency_tracker = self.latency_tracker
        t._deploy_gate = getattr(self, "_deploy_gate", None)
        if isinstance(t, SourceSubtask) and self.latency_interval_ms:
            t.latency_marker_interval_ms = self.latency_interval_ms
        if self.incremental:
            # delta checkpoints: the subtask opens the snapshot scope with
            # incremental=True (savepoints/finals excepted) and every
            # delta-capable operator in the chain starts dirty tracking
            t.incremental_checkpoints = True
            for member in getattr(t.operator, "operators", [t.operator]):
                if hasattr(member, "incremental_state"):
                    member.incremental_state = True
                    if hasattr(member, "incr_rebase_ratio"):
                        member.incr_rebase_ratio = \
                            self.incremental_rebase_ratio
                be = getattr(member, "backend", None)
                if be is not None and hasattr(be, "snapshot_increment"):
                    be.materialize_threshold = \
                        self.changelog_materialization_threshold

    def _wire_queryable(self, plan: ExecutionPlan) -> None:
        """Register every ``queryable=<name>`` operator's live views with
        the serving tier and stand up a checkpoint replica per state.
        Re-deploys (restarts, region recovery) RE-register views — the
        rebuilt operators publish fresh — while replicas persist (their
        last ingested checkpoint keeps serving through the restart)."""
        regs: Dict[str, Dict[str, Any]] = {}
        for t in self._tasks:
            op = t.operator
            for member in getattr(op, "operators", [op]):
                qname = getattr(member, "queryable", None)
                view = getattr(member, "queryable_view", lambda: None)()
                if qname is None or view is None:
                    continue
                entry = regs.setdefault(qname, {"uid": t.vertex_uid,
                                                "views": {}, "op": member})
                entry["views"][t.subtask_index] = view
        if not regs:
            return
        if self.queryable is None:
            from flink_tpu.metrics.groups import queryable_metrics
            from flink_tpu.queryable.service import QueryableStateService
            self.queryable = QueryableStateService()
            queryable_metrics(self.job_metric_group,
                              lambda: (self.queryable.stats()
                                       if self.queryable else None))
        max_par = {v.uid: v.max_parallelism
                   for v in plan.vertices} if plan is not None else {}
        for name, entry in regs.items():
            p = self._subtask_counts.get(entry["uid"], len(entry["views"]))
            views = [entry["views"].get(i) for i in range(p)]
            from flink_tpu.queryable.view import WindowReadView
            views = [v if v is not None else WindowReadView(
                entry["op"].key_column) for v in views]
            self.queryable.register_views(
                name, views, parallelism=p,
                max_parallelism=max_par.get(entry["uid"], 128))
            if name not in self.queryable.registry.replicas():
                from flink_tpu.queryable.replica import QueryableStateSpec
                self.queryable.add_replica(
                    name, QueryableStateSpec.from_operator(
                        name, entry["uid"], entry["op"]),
                    max_parallelism=max_par.get(entry["uid"], 128),
                    replicas=self.queryable_replicas)

    def start_queryable_server(self, host: str = "127.0.0.1", port: int = 0):
        """Start (or return) the job's TCP queryable-state server
        (``KvStateServerImpl`` analog) fronting the serving tier."""
        if self.queryable is None:
            from flink_tpu.queryable.service import QueryableStateService
            self.queryable = QueryableStateService()
        return self.queryable.start_server(host=host, port=port)

    def _iter_paged_operators(self):
        for t in getattr(self, "_tasks", []):
            op = t.operator
            for member in getattr(op, "operators", [op]):
                if getattr(member, "_pager", None) is not None:
                    yield member

    def device_health_status(self) -> Dict[str, Any]:
        """Process-wide device-lane health + this job's per-operator tier
        counters (``job_status()["device_health"]`` and the
        ``device_health.*`` gauges).  Monitoring-grade: reads no operator
        state behind a barrier."""
        from flink_tpu.runtime import device_health
        status = device_health.status_snapshot()
        degraded = migrations = repromotions = 0
        for t in getattr(self, "_tasks", []):
            op = t.operator
            for member in getattr(op, "operators", [op]):
                stats_fn = getattr(member, "device_health_stats", None)
                if stats_fn is None:
                    continue
                st = stats_fn()
                degraded += st.get("degraded", 0)
                migrations += st.get("quarantine_migrations", 0)
                repromotions += st.get("repromotions", 0)
        status["degraded_operators"] = degraded
        status["quarantine_migrations"] = migrations
        status["repromotions"] = repromotions
        return status

    def paging_totals(self) -> Optional[Dict[str, int]]:
        """Aggregated ``paging_stats()`` across every paged operator
        (job_status()["paging"] + the job-scope ``paging.*`` gauges)."""
        total: Optional[Dict[str, int]] = None
        for member in self._iter_paged_operators():
            st = member.paging_stats()
            if not st:
                continue
            if total is None:
                total = dict(st)
            else:
                for k, v in st.items():
                    total[k] = total.get(k, 0) + v
        return total

    def backpressure_totals(self) -> Dict[str, Any]:
        """Aggregated channel backpressure view (the ``backpressure.*``
        gauges): total producer credit-wait time, deepest input queue, and
        elements currently buffered by barrier alignment.  Monitoring-grade
        — reads channel counters only, no operator state."""
        total_ms = 0.0
        max_depth = 0
        queued = 0
        for t in getattr(self, "_tasks", []):
            chan_fn = getattr(t, "channel_stats", None)
            if chan_fn is None:
                continue
            for c in chan_fn():
                total_ms += c["backpressured_ms"]
                max_depth = max(max_depth, c["depth"])
            queued += t.alignment_queued
        return {"total_backpressured_ms": round(total_ms, 3),
                "max_queue_depth": max_depth,
                "alignment_queued_elements": queued}

    # ------------------------------------------------------------ triggers
    def trigger_checkpoint(self) -> Optional[int]:
        cid, _reason = self._trigger_checkpoint()
        return cid

    def _trigger_checkpoint(self, savepoint: bool = False
                            ) -> Tuple[Optional[int], str]:
        """Start one checkpoint: inject barriers at all sources (RPC analog,
        ``CheckpointCoordinator.triggerCheckpoint:502``).  Returns
        ``(id, "ok")``, ``(None, "busy")`` while one is in flight, or
        ``(None, "declined")`` when checkpointing is no longer possible.
        ``savepoint=True`` marks the barriers so subtasks keep the
        snapshot ALIGNED even under escalation (rescalable by contract)."""
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureReason

        with self._lock:
            if self._pending is not None:
                # expiry reads the injectable clock seam through a MONOTONE
                # elapsed tracker: a ClockSkew backward step can neither
                # un-expire a checkpoint nor extend its deadline
                if (self._pending.timer.seconds()
                        < self.checkpoint_timeout_s):
                    return None, "busy"   # previous still in flight
                expired = self._pending
                self._pending = None  # timed out: abort
                self._record_checkpoint_failure(
                    CheckpointFailureReason.TIMEOUT, expired.checkpoint_id,
                    f"{len(expired.acks)}/{expired.expected} acks after "
                    f"{self.checkpoint_timeout_s}s")
            if not self._tasks:
                return None, "declined"   # nothing deployed yet
            # finished sources cannot inject barriers and finished tasks
            # never ack — decline once any source finished, exclude finished
            # tasks from the expectation otherwise
            if any((t.vertex_uid, t.subtask_index) in self._finished
                   for t in self._source_tasks):
                return None, "declined"
            expected = len(self._tasks) - len(self._finished)
            if expected <= 0:
                return None, "declined"
            cid = self._next_checkpoint_id
            self._next_checkpoint_id += 1
            tracing.instant("checkpoint.trigger", cat="checkpoint",
                            checkpoint=cid, savepoint=savepoint)
            self._pending = _PendingCheckpoint(
                cid, expected=expected, timer=clock.MonotoneElapsed(),
                t0_ns=time.perf_counter_ns())
            coord = getattr(self, "_source_coordinator", None)
            if coord is not None and coord._enums:
                self._pending.enumerators = coord.snapshot()
        for t in self._source_tasks:
            t.commands.put(("checkpoint", cid, savepoint))
        return cid, "ok"

    # ------------------------------------------------------------ execute
    def execute(self, plan: ExecutionPlan,
                restore: Optional[Dict[str, Any]] = None,
                timeout_s: float = 300.0) -> JobResult:
        from flink_tpu.observability import tracing as tracing_mod

        if self.tracing_enabled:
            # one shared ownership state machine (see
            # tracing.acquire_for_execution): per-execution reset of an
            # owned ring, fresh owned ring when an adopted one's owner
            # released, (re-)adoption of whichever ring is actually live
            self._trace_journal, self._owns_trace_journal = \
                tracing_mod.acquire_for_execution(self._trace_journal,
                                                  self._owns_trace_journal)
        # the latency view is per execution too: job B's panel and
        # latency.* series must not mix in job A's hop rows/samples
        self.latency_tracker.reset()
        j, owned = self._trace_journal, self._owns_trace_journal
        try:
            return self._execute(plan, restore, timeout_s)
        finally:
            tracing_mod.release_after_execution(j, owned)

    def _execute(self, plan: ExecutionPlan,
                 restore: Optional[Dict[str, Any]],
                 timeout_s: float) -> JobResult:
        import copy as _copy

        if restore is not None:
            # a snapshot taken at a DIFFERENT parallelism (the autoscaler's
            # pre-rescale cut, an operator-resized redeploy) redistributes
            # through the key-group path — persisted in-flight channel
            # state included — instead of silently restoring positionally
            from flink_tpu.cluster.adaptive import maybe_rescale_restore
            restore = maybe_rescale_restore(restore, plan)
        self._plan = plan              # dashboard DAG view
        t0 = time.monotonic()
        restarts = 0
        self._restarts = 0
        # restart budgets are per execution (per-ExecutionGraph in the
        # reference): a fresh strategy instance each run
        self._active_strategy = _copy.deepcopy(self.restart_strategy)
        self._deploy(plan, restore)
        # trigger cadence through the clock seam, monotone under skew
        trigger_timer = clock.MonotoneElapsed()
        while True:
            time.sleep(0.002)
            if time.monotonic() - t0 > timeout_s:
                self.cancel()
                return JobResult(plan.job_name, TaskStates.CANCELED,
                                 (time.monotonic() - t0) * 1000, restarts,
                                 self._completed_ids, "timeout")
            if self._failed is not None:
                err = self._failed
                failed_uid = err.split("[", 1)[0]
                self._active_strategy.notify_failure()
                if self._active_strategy.can_restart():
                    restarts += 1
                    self._restarts = restarts
                    # in-flight checkpoint attempts die with the execution:
                    # the continuous-failure window restarts too
                    self.failure_manager.on_job_restart()
                    time.sleep(self._active_strategy.delay_ms() / 1000.0)
                    self._restart_failed_region(plan, failed_uid)
                    continue
                self.cancel()
                for t in self._tasks:
                    t.join()
                return JobResult(plan.job_name, TaskStates.FAILED,
                                 (time.monotonic() - t0) * 1000, restarts,
                                 self._completed_ids, err)
            states = [t.state for t in self._tasks]
            terminal = (TaskStates.FINISHED, TaskStates.CANCELED)
            if all(s in terminal for s in states):
                final = (TaskStates.FINISHED
                         if all(s == TaskStates.FINISHED for s in states)
                         else TaskStates.CANCELED)
                return JobResult(plan.job_name, final,
                                 (time.monotonic() - t0) * 1000, restarts,
                                 self._completed_ids)
            if (self.checkpoint_interval_ms and
                    trigger_timer.ms() >= self.checkpoint_interval_ms):
                if self.trigger_checkpoint() is not None:
                    trigger_timer = clock.MonotoneElapsed()

    def _restart_failed_region(self, plan: ExecutionPlan,
                               failed_uid: str) -> None:
        """Pipelined-region failover: restart only the connected component
        containing the failed vertex (``RestartPipelinedRegionFailover
        Strategy``); disconnected regions keep running."""
        from flink_tpu.cluster.failover import region_of

        try:
            region = region_of(plan, failed_uid)
        except KeyError:
            region = {v.uid for v in plan.vertices}
        latest = self.latest_restore()
        if latest is not None:
            # a worker dying MID-RESCALE restarts against a checkpoint the
            # previous parallelism wrote (storage outlives the redeploy):
            # redistribute it — keyed state AND persisted in-flight
            # channel state — instead of restoring positionally into the
            # wrong subtask count (the idempotent-re-trigger contract)
            from flink_tpu.cluster.adaptive import maybe_rescale_restore
            latest = maybe_rescale_restore(latest, plan)
        all_uids = {v.uid for v in plan.vertices}
        if region == all_uids:
            self.cancel()
            for t in self._tasks:
                t.join()
            self._deploy(plan, latest)
            return
        # pin uids: the region sub-plan re-runs topo indexing, and
        # position-derived uids would shift — snapshots key on them
        for v in plan.vertices:
            if not any(t.uid for t in v.chain):
                v.chain[0].uid = v.uid
        # cancel + drop only the failed region's tasks, keep the rest
        keep, dead = [], []
        for t in self._tasks:
            (dead if t.vertex_uid in region else keep).append(t)
        for t in dead:
            t.cancel()
        for t in dead:
            t.join()
        survivors = keep
        with self._lock:
            # only clear the failure we are handling: a DIFFERENT region may
            # have failed in the meantime and must get its own restart
            if self._failed is not None and \
                    self._failed.split("[", 1)[0] in region:
                self._failed = None
            self._pending = None
            self._finished = {f for f in self._finished
                              if f[0] not in region}
        region_plan = ExecutionPlan(
            [v for v in plan.vertices if v.uid in region], plan.job_name)
        self._deploy(region_plan, latest, _keep_tasks=survivors)

    def latest_restore(self) -> Optional[Dict[str, Any]]:
        """Most recent restorable snapshot: durable storage first, else the
        in-memory copy of the last completed checkpoint.  A storage read
        failure (checkpoint.load fault, transient error) degrades to the
        in-memory copy (or scratch) instead of escaping execute() — the
        restart attempt must stay inside the restart machinery."""
        if self.checkpoint_storage is not None:
            try:
                loaded = self.checkpoint_storage.load_latest()
            except Exception:  # noqa: BLE001
                loaded = None
            if loaded is not None:
                return loaded
        return getattr(self, "_latest_snapshot", None)

    def cancel(self) -> None:
        for t in self._tasks:
            t.cancel()

    # ------------------------------------------------------- introspection
    def execution_plan_view(self) -> Dict[str, Any]:
        """DAG topology for the dashboard (JobGraph REST view analog):
        vertices (id, name, parallelism) + edges (source, target,
        partitioning)."""
        plan = getattr(self, "_plan", None)
        if plan is None:
            return {"vertices": [], "edges": []}
        edges = []
        for v in plan.vertices:
            for e in v.out_edges:
                edges.append({"source": v.id, "target": e.target_id,
                              "partitioning": str(getattr(
                                  e, "partitioning", ""))})
        return {"vertices": [{"id": v.id, "name": v.name,
                              "parallelism": v.parallelism}
                             for v in plan.vertices],
                "edges": edges}

    def job_status(self) -> Dict[str, Any]:
        """REST-facing job view (jobs/<id> handler backing)."""
        tasks = getattr(self, "_tasks", [])
        by_vertex: Dict[str, List] = {}
        for t in tasks:
            by_vertex.setdefault(t.vertex_uid, []).append(t)
        plan = getattr(self, "_plan", None)
        # tasks key on v.uid (the stable operator id), not the int plan id
        names = ({v.uid: v.name for v in plan.vertices} if plan is not None
                 else {})
        vertices = []
        for uid, ts in by_vertex.items():
            total_ns = max(1, sum(t.busy_ns + t.idle_ns + t.backpressure_ns
                                  for t in ts))

            def ratios(t):
                tot = max(1, t.busy_ns + t.idle_ns + t.backpressure_ns)
                return (t.busy_ns / tot, t.idle_ns / tot,
                        t.backpressure_ns / tot)

            subtasks = []
            for t in sorted(ts, key=lambda t: t.subtask_index):
                b, i, bp = ratios(t)
                entry = {
                    "index": t.subtask_index, "state": t.state,
                    "records_in": t.records_in,
                    "records_out": t.records_out,
                    "busy_ratio": b, "idle_ratio": i,
                    "backpressure_ratio": bp}
                # channel-consuming subtasks: per-channel queue depth /
                # backpressured time + the alignment-queue gauge
                chan_fn = getattr(t, "channel_stats", None)
                if chan_fn is not None:
                    entry["channels"] = chan_fn()
                    entry["alignment_queued"] = t.alignment_queued
                    entry["alignment_queue_peak"] = t.alignment_queue_peak
                subtasks.append(entry)
            vertices.append({
                "id": uid,
                "name": names.get(uid, str(uid)),
                "parallelism": len(ts),
                "status": sorted({t.state for t in ts}),
                "records_in": sum(t.records_in for t in ts),
                "records_out": sum(t.records_out for t in ts),
                "busy_ratio": sum(t.busy_ns for t in ts) / total_ns,
                "idle_ratio": sum(t.idle_ns for t in ts) / total_ns,
                "backpressure_ratio":
                    sum(t.backpressure_ns for t in ts) / total_ns,
                "watermark": _vertex_watermark(ts),
                "subtasks": subtasks,
            })
        states = [t.state for t in tasks]
        terminal = (TaskStates.FINISHED, TaskStates.CANCELED)
        if self._failed is not None:
            job_state = "FAILED"
        elif states and all(s == TaskStates.FINISHED for s in states):
            job_state = "FINISHED"
        elif states and all(s in terminal for s in states):
            job_state = "CANCELED"
        elif states:
            job_state = "RUNNING"
        else:
            job_state = "CREATED"
        journal = self._trace_journal
        checkpoints = self.failure_manager.status()
        # top-level "completed_checkpoints" is the LIST of ids; this is the
        # lifetime count — name it distinctly so consumers can't mix them up
        checkpoints["num_completed_checkpoints"] = self.failure_manager \
            .num_completed()
        # unaligned-checkpoint accounting of the LAST completed checkpoint
        # (alignment critical path, overtaken + persisted in-flight bytes)
        checkpoints.update(self._last_alignment)
        paging = self.paging_totals()
        autoscaler = None
        if self.autoscaler_status_supplier is not None:
            try:
                autoscaler = self.autoscaler_status_supplier()
            except Exception:  # noqa: BLE001 — monitoring must not fail status
                autoscaler = None
        ha = None
        if self.ha_status_supplier is not None:
            try:
                ha = self.ha_status_supplier()
            except Exception:  # noqa: BLE001 — monitoring must not fail status
                ha = None
        return {
            **({"paging": paging} if paging is not None else {}),
            **({"queryable": self.queryable.stats()}
               if self.queryable is not None else {}),
            **({"autoscaler": autoscaler} if autoscaler is not None else {}),
            **({"ha": ha} if ha is not None else {}),
            "device_health": self.device_health_status(),
            #: per-(source, hop) latency percentiles (LatencyMarker flow)
            "latency": self.latency_tracker.panel(),
            #: span-journal rollup (full export: trace_events() / REST
            #: GET /jobs/<id>/trace)
            "trace": (journal.summary() if journal is not None
                      else {"enabled": False, "spans": 0, "dropped": 0}),
            "state": job_state,
            "vertices": vertices,
            "completed_checkpoints": list(self._completed_ids),
            "checkpoint_stats": list(self._checkpoint_stats),
            #: failed-checkpoint counters + tolerable budget (the
            #: CheckpointFailureManager view) and restart count
            "checkpoints": checkpoints,
            "failed_checkpoints": self.failure_manager.num_failed(),
            "restarts": self._restarts,
            "exception_history": list(self._exception_history),
            "failure": self._failed,
        }

    def trace_events(self) -> Dict[str, Any]:
        """Chrome trace-event export of the process span journal
        (Perfetto-loadable; REST ``GET /jobs/<id>/trace`` backing)."""
        journal = self._trace_journal
        if journal is None:
            return {"traceEvents": [], "displayTimeUnit": "ms",
                    "otherData": {"enabled": False}}
        snap = journal.snapshot()
        return {"traceEvents": tracing.to_chrome(snap, pid=0,
                                                 process_name="minicluster"),
                "displayTimeUnit": "ms",
                "otherData": {"enabled": True,
                              "dropped_spans": snap["dropped"],
                              "latency": self.latency_tracker.panel()}}

    def sink_latencies_ms(self) -> List[float]:
        out: List[float] = []
        for t in getattr(self, "_tasks", []):
            op = t.operator
            ops = getattr(op, "operators", [op])
            for member in ops:
                out.extend(getattr(member, "latencies_ms", []))
        return out

    def savepoint(self) -> Optional[int]:
        """User-triggered checkpoint (savepoint analog): returns its id once
        completed, or None if it could not complete.  Savepoint barriers
        never escalate to unaligned — the snapshot stays rescalable and
        rewritable even without channel-state redistribution."""
        return self._triggered_checkpoint(savepoint=True)

    def checkpoint(self, timeout_s: Optional[float] = None) -> Optional[int]:
        """A fresh consistent cut of the RUNNING job — the rescale-under-
        fire primitive: returns the id of the next checkpoint to COMPLETE
        after this call (triggering one itself whenever no periodic
        attempt holds the slot).  Unlike :meth:`savepoint` the cut's
        barriers MAY escalate to unaligned under backpressure, so it
        completes in bounded time exactly when the job is drowning, and
        its persisted in-flight channel state redistributes by key on
        restore at a different parallelism
        (``state/redistribute.redistribute_channel_state``).  Adopting
        the next completed id (rather than insisting on its own trigger)
        matters on jobs with a short checkpoint interval: every completed
        checkpoint is an equally valid cut, and racing the periodic
        trigger loop for the pending slot could starve past any budget.
        Returns None when no cut is possible (sources finished)."""
        budget = (timeout_s if timeout_s is not None
                  else self.checkpoint_timeout_s)
        deadline = time.monotonic() + budget
        with self._lock:
            baseline = max(self._completed_ids, default=0)
        while time.monotonic() < deadline:
            with self._lock:
                newer = [c for c in self._completed_ids if c > baseline]
                if newer:
                    return max(newer)
                if self._failed is not None:
                    return None
            _cid, reason = self._trigger_checkpoint()
            if reason == "declined":
                return None    # permanently impossible (sources done)
            time.sleep(0.005)
        return None

    def _triggered_checkpoint(self, savepoint: bool,
                              timeout_s: Optional[float] = None
                              ) -> Optional[int]:
        budget = (timeout_s if timeout_s is not None
                  else self.checkpoint_timeout_s)
        cid = None
        deadline0 = time.monotonic() + budget
        while cid is None and time.monotonic() < deadline0:
            cid, reason = self._trigger_checkpoint(savepoint=savepoint)
            if cid is None:
                if reason == "declined":
                    return None    # permanently impossible (sources done)
                # a periodic checkpoint is in flight: wait for its slot
                time.sleep(0.005)
        if cid is None:
            return None
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            with self._lock:
                if cid in self._completed_ids:
                    return cid
                if self._failed is not None:
                    return None
            time.sleep(0.005)
        return None

    def stop_with_savepoint(self) -> Optional[int]:
        """``flink stop`` analog: PAUSE the sources, take a savepoint, then
        cancel — pausing first means no record is processed after the
        savepoint's barrier, so the returned id restores a successor run
        exactly where this one stopped (the reference suspends sources at
        the stop barrier for the same reason).  None if no savepoint could
        complete; sources resume in that case and the job keeps running."""
        for t in self._source_tasks:
            t._paused.set()
        sp = self.savepoint()
        if sp is None:
            for t in self._source_tasks:
                t._paused.clear()
            return None
        self.cancel()
        return sp
