"""Cross-host data plane: TCP channels with credit-based flow control.

The inter-host analog of the reference's Netty shuffle
(``NettyServer.java`` / ``NettyMessage.java``: ``PartitionRequest``,
``BufferResponse:254``, ``AddCredit:678``; credit accounting in
``RemoteInputChannel.java:101,302``): intra-pod record exchange rides device
collectives (``parallel/exchange.py``), and THIS module is the host/DCN tier
— one :class:`ChannelServer` per receiving process, writers connect per
logical channel, record batches travel as FTB frames (the native codec, with
block compression), control elements as JSON frames.

Flow control mirrors the reference's credit protocol: the receiver grants an
initial per-channel credit budget (its buffer queue capacity); every element
costs one credit; the consumer draining its queue returns credits to the
sender.  A writer with zero credits blocks — the sender-side backpressure
that keeps a slow consumer from being buried (never TCP head-of-line
blocking across channels: each channel has its own connection + budget).

Wire format per frame:  ``type u8 | length u32le | payload``
  type 0 = RecordBatch (FTB), 1 = control element (JSON),
  type 2 = credit grant (receiver -> sender, count u32 payload),
  type 3 = handshake (sender -> receiver:
           ``mac_len u8 | mac | channel id utf-8``),
  type 4 = tagged batch (side output): tag length u16le | tag utf-8 | FTB,
  type 5 = challenge (receiver -> sender on accept: nonce bytes).

**Authentication:** batches carry pickled object columns, so the receiver
must never decode a frame from an unauthenticated peer.  On accept the
server sends a ``_CHALLENGE`` nonce; the sender's HELLO carries
``HMAC-SHA256(token, nonce + channel_id)``.  A server configured with an
``auth_token`` drops any connection whose MAC fails BEFORE decoding
anything else; TLS (mutual) is layered underneath via ``ssl_context``.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import os
import socket
import struct
import threading
from collections import deque
from typing import Dict, Optional

from flink_tpu.core.batch import (CheckpointBarrier, EndOfInput,
                                  LatencyMarker, RecordBatch, StreamElement,
                                  StreamStatus, TaggedBatch, Watermark)

_HDR = struct.Struct("<BI")
_BATCH, _CONTROL, _CREDIT, _HELLO, _TAGGED, _CHALLENGE = 0, 1, 2, 3, 4, 5


def _mac(token: str, nonce: bytes, channel_id: bytes) -> bytes:
    return hmac_mod.new(token.encode(), nonce + channel_id,
                        hashlib.sha256).digest()


_LOOPBACK = ("127.0.0.1", "localhost", "::1")


def require_secure_bind(host: str, has_tls: bool, role: str,
                        detail: str = "") -> None:
    """Single policy for every listening endpoint: a non-loopback bind
    requires TLS (the reference's ``security.ssl.internal.enabled``
    posture); ``FLINK_TPU_ALLOW_INSECURE=1`` overrides for trusted
    networks.  Token-only auth gates handshakes but cannot stop an on-path
    attacker injecting frames into an established stream — hence TLS."""
    if host in _LOOPBACK or has_tls:
        return
    if os.environ.get("FLINK_TPU_ALLOW_INSECURE") == "1":
        return
    raise ValueError(
        f"{role} would bind {host!r} (non-loopback) without TLS{detail}; "
        f"configure mutual TLS or set FLINK_TPU_ALLOW_INSECURE=1 for a "
        f"trusted network")


def _encode_control(el: StreamElement) -> bytes:
    if isinstance(el, Watermark):
        d = {"t": "wm", "ts": el.timestamp}
    elif isinstance(el, CheckpointBarrier):
        d = {"t": "barrier", "id": el.checkpoint_id, "ts": el.timestamp,
             "sp": el.is_savepoint}
    elif isinstance(el, EndOfInput):
        d = {"t": "eoi"}
    elif isinstance(el, StreamStatus):
        d = {"t": "status", "idle": el.idle}
    elif isinstance(el, LatencyMarker):
        d = {"t": "latency", "mt": el.marked_time, "src": el.source_id,
             "sub": el.subtask_index, "name": el.source}
    else:
        raise TypeError(f"not wire-encodable: {type(el).__name__}")
    return json.dumps(d).encode()


def _decode_control(payload: bytes) -> StreamElement:
    d = json.loads(payload)
    t = d["t"]
    if t == "wm":
        return Watermark(d["ts"])
    if t == "barrier":
        return CheckpointBarrier(d["id"], d["ts"], d["sp"])
    if t == "eoi":
        return EndOfInput()
    if t == "status":
        return StreamStatus(d["idle"])
    if t == "latency":
        return LatencyMarker(d["mt"], d["src"], d["sub"], d.get("name", ""))
    raise ValueError(f"unknown control frame {t!r}")


def _send_frame(sock: socket.socket, ftype: int, payload: bytes) -> None:
    sock.sendall(_HDR.pack(ftype, len(payload)) + payload)


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes or return None on EOF — the shared socket
    primitive of every framed protocol in the repo (data plane here, the
    queryable serving tier's wire layer, the control planes)."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


_recv_exact = recv_exact


def _recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None, None
    ftype, ln = _HDR.unpack(hdr)
    payload = _recv_exact(sock, ln) if ln else b""
    if ln and payload is None:
        return None, None
    return ftype, payload


class _ReceiveQueue:
    """Server-side channel queue; polling returns credits to the sender
    (``RemoteInputChannel.notifyCreditAvailable`` direction)."""

    def __init__(self, capacity: int, name: str = ""):
        self.capacity = capacity
        self.name = name
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._conn: Optional[socket.socket] = None
        self._closed = False
        #: remote channels measure producer credit-waits sender-side; the
        #: consumer-side gauge stays 0 here (shape parity w/ LocalChannel)
        self.backpressured_ns = 0
        #: queued-barrier announcement (LocalChannel contract)
        self._announced: deque = deque()

    def _attach(self, conn: socket.socket) -> None:
        with self._lock:
            self._conn = conn

    def _push(self, el: StreamElement) -> None:
        from flink_tpu.core.batch import CheckpointBarrier
        with self._not_empty:
            self._q.append(el)
            if isinstance(el, CheckpointBarrier):
                self._announced.append(el.checkpoint_id)
            self._not_empty.notify()

    def announced_barrier(self) -> Optional[int]:
        with self._lock:
            return self._announced[0] if self._announced else None

    def poll(self, timeout_s: float = 0.0) -> Optional[StreamElement]:
        from flink_tpu.core.batch import CheckpointBarrier
        with self._not_empty:
            if not self._q and timeout_s > 0:
                self._not_empty.wait(timeout=timeout_s)
            if not self._q:
                return None
            el = self._q.popleft()
            if isinstance(el, CheckpointBarrier) and self._announced:
                self._announced.popleft()
            conn = self._conn
        if conn is not None:
            try:
                _send_frame(conn, _CREDIT, struct.pack("<I", 1))
            except OSError:
                pass
        # slow-consumer drain stall (chaos.SlowConsumer) — after the credit
        # returns so the stall models the CONSUMER, not the link
        from flink_tpu.testing import chaos
        chaos.fire("channel.recv", channel=self.name)
        return el

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def queued_bytes(self) -> int:
        from flink_tpu.cluster.channels import element_bytes
        with self._lock:
            return sum(element_bytes(el) for el in self._q)

    def take_until_barrier(self, checkpoint_id: int):
        """Barrier overtake on a remote input channel: extract the queued
        elements in front of checkpoint ``checkpoint_id``'s barrier (the
        SHARED extraction loop of ``channels.take_until_barrier_locked`` —
        returns the consumed barrier element or None).  Credits for every
        consumed element (barrier included) still flow back to the
        sender."""
        from flink_tpu.cluster.channels import take_until_barrier_locked
        with self._not_empty:
            out, barrier = take_until_barrier_locked(
                self._q, self._announced, checkpoint_id)
            conn = self._conn
        credits = len(out) + (1 if barrier is not None else 0)
        if conn is not None and credits:
            try:
                _send_frame(conn, _CREDIT, struct.pack("<I", credits))
            except OSError:
                pass
        return out, barrier

    def close(self) -> None:
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class ChannelServer:
    """Receiving endpoint: one TCP server, one queue per logical channel.

    ``ssl_context``: a server-side context (mutual TLS — see
    ``security/ssl_context.py``) wraps every accepted connection, the
    ``security.ssl.internal.enabled`` data-plane encryption of the
    reference."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 channel_capacity: int = 32, ssl_context=None,
                 auth_token: Optional[str] = None):
        require_secure_bind(host, ssl_context is not None, "ChannelServer",
                            detail=" (batches carry pickled columns)")
        #: coordinator HA (ISSUE-20): data-plane epoch fence — a channel
        #: HELLO carrying a LOWER (non-zero) leader epoch is a stale
        #: incarnation's writer and is rejected before any decode.  Workers
        #: raise this as they adopt higher epochs; 0 admits everything.
        self.min_epoch = 0
        self.channel_capacity = channel_capacity
        self._ssl = ssl_context
        self._auth_token = auth_token
        self._queues: Dict[str, _ReceiveQueue] = {}
        self._lock = threading.Lock()
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="channel-server", daemon=True)
        self._thread.start()

    def channel(self, channel_id: str) -> _ReceiveQueue:
        """The consumer-side queue (poll/close/len — LocalChannel shape)."""
        with self._lock:
            q = self._queues.get(channel_id)
            if q is None:
                q = self._queues[channel_id] = _ReceiveQueue(
                    self.channel_capacity, name=channel_id)
            return q

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        from flink_tpu.native.codec import decode_batch

        try:
            if self._ssl is not None:
                # handshake on the connection thread (it can block)
                conn = self._ssl.wrap_socket(conn, server_side=True)
            # a pre-auth peer must not stall the thread or feed us frames:
            # bounded handshake window, MAC verified before ANY decode
            conn.settimeout(30)
            nonce = os.urandom(32)
            _send_frame(conn, _CHALLENGE, nonce)
            ftype, payload = _recv_frame(conn)
            if ftype != _HELLO or not payload:
                conn.close()
                return
            mac_len = payload[0]
            mac, rest = payload[1:1 + mac_len], payload[1 + mac_len:]
            if len(rest) < 8:
                conn.close()
                return
            (epoch,) = struct.unpack("<Q", rest[:8])
            chan = rest[8:]
            if self._auth_token is not None and not hmac_mod.compare_digest(
                    _mac(self._auth_token, nonce, rest), mac):
                conn.close()
                return
            if epoch and epoch < self.min_epoch:
                # stale-incarnation writer (zombie ex-leader's deploy):
                # reject before attaching — its batches never decode
                conn.close()
                return
            conn.settimeout(None)
            q = self.channel(chan.decode())
            q._attach(conn)
            # initial credit grant = queue capacity (exclusive buffers)
            _send_frame(conn, _CREDIT, struct.pack("<I", q.capacity))
            while not self._stop.is_set():
                ftype, payload = _recv_frame(conn)
                if ftype is None:
                    return
                if ftype == _BATCH:
                    q._push(decode_batch(payload))
                elif ftype == _CONTROL:
                    q._push(_decode_control(payload))
                elif ftype == _TAGGED:
                    (tlen,) = struct.unpack("<H", payload[:2])
                    tag = payload[2:2 + tlen].decode()
                    q._push(TaggedBatch(tag,
                                        decode_batch(payload[2 + tlen:])))
        except (OSError, ValueError):
            return
        finally:
            conn.close()

    def reset(self) -> None:
        """Drop all channel queues (worker recovery: fresh deploys create
        fresh channels; stale connections keep pushing into the detached
        old queues, which nothing polls).  The server socket stays up — the
        worker's advertised address survives the recovery."""
        with self._lock:
            old = list(self._queues.values())
            self._queues = {}
        for q in old:
            q.close()

    def reset_channels(self, channel_ids) -> None:
        """Region-scoped recovery: drop ONLY these channels' queues (the
        affected region's), leaving unaffected regions' channels streaming
        undisturbed."""
        with self._lock:
            old = [self._queues.pop(cid) for cid in channel_ids
                   if cid in self._queues]
        for q in old:
            q.close()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for q in self._queues.values():
                q.close()


class RemoteChannel:
    """Sender side: LocalChannel-shaped ``put`` over TCP with credits."""

    def __init__(self, host: str, port: int, channel_id: str,
                 connect_timeout_s: float = 10.0, ssl_context=None,
                 auth_token: Optional[str] = None, epoch: int = 0):
        self.channel_id = channel_id
        #: leader epoch this writer was deployed under (ISSUE-20); the
        #: HELLO carries it and servers reject stale incarnations
        self.epoch = int(epoch)
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout_s)
        if ssl_context is not None:
            self._sock = ssl_context.wrap_socket(self._sock,
                                                 server_hostname=host)
        self._sock.settimeout(None)
        self._auth_token = auth_token
        self._credits = 0
        self._lock = threading.Lock()
        self._have_credit = threading.Condition(self._lock)
        self._closed = False
        #: set when the connection died before the server ever granted
        #: credit — a rejected handshake (auth failure), which must surface
        #: as an error, not as silent backpressure-drop
        self._error: Optional[str] = None
        self._got_credit = False
        self._reader = threading.Thread(target=self._credit_loop,
                                        name=f"credits-{channel_id}",
                                        daemon=True)
        self._reader.start()

    def _credit_loop(self) -> None:
        # answer the server's challenge first (HELLO carries the HMAC over
        # nonce + channel id); credits only start flowing once the server
        # accepted it, so put() blocks until the channel is authenticated
        try:
            ftype, nonce = _recv_frame(self._sock)
            if ftype != _CHALLENGE:
                raise OSError("bad data-plane challenge")
            # HELLO = mac_len | mac | epoch u64 | channel id; the MAC
            # covers epoch + channel id, so a stale epoch cannot be
            # stripped or rewritten by an on-path peer
            rest = struct.pack("<Q", self.epoch) + self.channel_id.encode()
            mac = (_mac(self._auth_token, nonce, rest)
                   if self._auth_token else b"")
            _send_frame(self._sock, _HELLO, bytes([len(mac)]) + mac + rest)
        except OSError as e:
            with self._have_credit:
                self._closed = True
                self._error = f"channel {self.channel_id}: handshake failed ({e})"
                self._have_credit.notify_all()
            return
        while True:
            try:
                ftype, payload = _recv_frame(self._sock)
            except OSError:
                ftype = None  # reset by peer == closed
            if ftype is None:
                with self._have_credit:
                    if not self._got_credit and not self._closed \
                            and self._auth_token is not None:
                        # server hung up before the initial credit grant on
                        # an authenticated channel: the HELLO was rejected
                        # (bad/missing MAC).  A local close() or a token-less
                        # channel stays a benign close (put returns False).
                        self._error = (
                            f"channel {self.channel_id}: connection rejected "
                            f"before any credit grant — data-plane "
                            f"authentication failed (token mismatch?)")
                    self._closed = True
                    self._have_credit.notify_all()
                return
            if ftype == _CREDIT:
                (n,) = struct.unpack("<I", payload)
                with self._have_credit:
                    self._got_credit = True
                    self._credits += n
                    self._have_credit.notify_all()

    def put(self, el: StreamElement,
            timeout_s: Optional[float] = None) -> bool:
        from flink_tpu.native.codec import encode_batch

        with self._have_credit:
            while self._credits <= 0 and not self._closed:
                if not self._have_credit.wait(timeout=timeout_s):
                    return False
            if self._closed:
                if self._error is not None:
                    # auth rejection: dropping silently would let the job
                    # "succeed" with missing data — fail the producer task
                    raise ConnectionError(self._error)
                return False
            self._credits -= 1
        try:
            if isinstance(el, RecordBatch):
                _send_frame(self._sock, _BATCH, encode_batch(el))
            elif isinstance(el, TaggedBatch):
                tag = el.tag.encode()
                _send_frame(self._sock, _TAGGED,
                            struct.pack("<H", len(tag)) + tag
                            + encode_batch(el.batch))
            else:
                _send_frame(self._sock, _CONTROL, _encode_control(el))
            return True
        except OSError:
            with self._have_credit:
                self._closed = True
            return False

    def close(self) -> None:
        with self._have_credit:
            self._closed = True
            self._have_credit.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
