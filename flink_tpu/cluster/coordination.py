"""Control plane: Dispatcher, JobMaster, ResourceManager, TaskExecutor.

Analogs of the reference's coordination endpoints (``Dispatcher.java:100``
``submitJob:299``, ``JobMaster.java:126`` ``startJobExecution:862``,
``resourcemanager/`` + ``slotmanager/SlotManager.java``,
``taskexecutor/TaskExecutor.java:181``), built on the single-threaded
RPC endpoints of :mod:`flink_tpu.cluster.rpc` (the Akka analog — same
main-thread discipline, ``MainThreadValidatorUtil``).

Deployment model: slots are the scheduling currency exactly as in the
reference — TaskExecutors register slots with the ResourceManager, a
JobMaster declares requirements, the SlotManager matches.  On a granted
allocation the JobMaster runs its job's data plane as a MiniCluster sized
to the granted slots (threads + channels — the in-process execution tier);
multi-host deployments put these same gateways behind a network transport,
which is the seam ``RpcService.connect`` isolates (SURVEY §5.8).

The Dispatcher persists submitted job graphs through
:class:`flink_tpu.cluster.ha.HaServices` and recovers them on start —
leader failover re-submits unfinished jobs (``Dispatcher`` recovery path).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.cluster.heartbeat import HeartbeatManager
from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.cluster.rpc import RpcEndpoint, RpcService, await_future


@dataclass
class SlotOffer:
    task_executor: str
    slot_id: int


class SlotManager:
    """Slot bookkeeping inside the ResourceManager
    (``SlotManager.java:50``): registered executor slots, allocation
    matching, release on executor loss."""

    def __init__(self):
        self._slots: Dict[Tuple[str, int], Optional[str]] = {}  # -> job_id

    def register_executor(self, te: str, num_slots: int) -> None:
        for s in range(num_slots):
            self._slots.setdefault((te, s), None)

    def unregister_executor(self, te: str) -> List[str]:
        """Remove an executor; returns job ids that lost slots."""
        lost = []
        for key in [k for k in self._slots if k[0] == te]:
            if self._slots[key] is not None:
                lost.append(self._slots[key])
            del self._slots[key]
        return sorted(set(lost))

    def free_slots(self) -> int:
        return sum(1 for v in self._slots.values() if v is None)

    def total_slots(self) -> int:
        return len(self._slots)

    def allocate(self, job_id: str, n: int) -> Optional[List[SlotOffer]]:
        free = [k for k, v in self._slots.items() if v is None]
        if len(free) < n:
            return None
        granted = free[:n]
        for k in granted:
            self._slots[k] = job_id
        return [SlotOffer(te, sid) for te, sid in granted]

    def release_job(self, job_id: str) -> int:
        n = 0
        for k, v in self._slots.items():
            if v == job_id:
                self._slots[k] = None
                n += 1
        return n


class TaskExecutorEndpoint(RpcEndpoint):
    """Worker agent (``TaskExecutor.java:181``): registers its slots with
    the ResourceManager and answers heartbeats."""

    def __init__(self, name: str, num_slots: int = 1):
        super().__init__(name)
        self.num_slots = num_slots
        self.last_heartbeat = 0.0

    def heartbeat(self) -> str:
        self.validate_runs_in_main_thread()
        self.last_heartbeat = time.monotonic()
        return self.name

    def slot_report(self) -> Tuple[str, int]:
        self.validate_runs_in_main_thread()
        return self.name, self.num_slots


class ResourceManagerEndpoint(RpcEndpoint):
    """Slot broker (``resourcemanager/`` + declarative ``SlotManager``)."""

    def __init__(self, rpc: RpcService, name: str = "resourcemanager",
                 heartbeat_interval_s: float = 0.2,
                 heartbeat_timeout_s: float = 1.0):
        super().__init__(name)
        self.rpc = rpc
        self.slot_manager = SlotManager()
        self._executors: Dict[str, Any] = {}
        self._lost_slot_listeners: List[Callable[[List[str]], None]] = []
        self._hb = HeartbeatManager(
            heartbeat_interval_s, heartbeat_timeout_s,
            on_timeout=self._executor_timed_out)

    def on_start(self) -> None:
        self._hb.start()

    def on_stop(self) -> None:
        self._hb.stop()

    def add_lost_slot_listener(self, fn: Callable[[List[str]], None]) -> None:
        self._lost_slot_listeners.append(fn)

    def register_task_executor(self, te_address: str) -> int:
        self.validate_runs_in_main_thread()
        gw = self.rpc.connect(te_address)
        te, slots = await_future(gw.slot_report())
        self.slot_manager.register_executor(te, slots)
        self._executors[te] = gw

        def ping(addr=te_address):
            try:
                g = self.rpc.connect(addr)
                name = await_future(g.heartbeat(), timeout_s=2.0)
                self._hb.receive_heartbeat(name)
            except (ConnectionError, Exception):  # noqa: BLE001
                pass

        from flink_tpu.cluster.heartbeat import HeartbeatTarget
        self._hb.monitor_target(te, HeartbeatTarget(ping))
        return slots

    def _executor_timed_out(self, te: str) -> None:
        # heartbeat thread -> marshal into the endpoint main thread
        self.run_async(self._drop_executor, te)

    def _drop_executor(self, te: str) -> None:
        self.validate_runs_in_main_thread()
        self._executors.pop(te, None)
        self._hb.unmonitor_target(te)
        lost_jobs = self.slot_manager.unregister_executor(te)
        for fn in self._lost_slot_listeners:
            fn(lost_jobs)

    def request_slots(self, job_id: str, n: int) -> Optional[List[SlotOffer]]:
        self.validate_runs_in_main_thread()
        return self.slot_manager.allocate(job_id, n)

    def release_slots(self, job_id: str) -> int:
        self.validate_runs_in_main_thread()
        return self.slot_manager.release_job(job_id)

    def overview(self) -> Dict[str, int]:
        self.validate_runs_in_main_thread()
        return {"task_executors": len(self._executors),
                "slots_total": self.slot_manager.total_slots(),
                "slots_free": self.slot_manager.free_slots()}


class JobMasterEndpoint(RpcEndpoint):
    """Per-job coordinator (``JobMaster.java:126``): acquires slots from the
    RM, runs the data plane, reports status, handles cancel/savepoint."""

    def __init__(self, job_id: str, plan, rpc: RpcService,
                 rm_address: str, parallelism: int,
                 checkpoint_storage=None, checkpoint_interval_ms: int = 0,
                 on_finished: Optional[Callable[[str, Any], None]] = None):
        super().__init__(f"jobmaster-{job_id}")
        self.job_id = job_id
        self.plan = plan
        self.rpc = rpc
        self.rm_address = rm_address
        self.parallelism = parallelism
        self.checkpoint_storage = checkpoint_storage
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.on_finished = on_finished
        self.status = "CREATED"
        self.slots: List[SlotOffer] = []
        self.cluster: Optional[MiniCluster] = None
        self.result = None
        self._exec_thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- lifecycle -----------------------------------------------------------
    def start_job_execution(self, restore=None) -> str:
        self.validate_runs_in_main_thread()
        if self.status == "RUNNING" or self._stopped:
            return self.status
        rm = self.rpc.connect(self.rm_address)
        offers = await_future(rm.request_slots(self.job_id, self.parallelism))
        if offers is None:
            self.status = "WAITING_FOR_RESOURCES"
            # declarative slot waiting: retry until resources appear
            # (reference: pending slot requests fulfilled by the SlotPool when
            # offers arrive; polling is the single-process equivalent)
            t = threading.Timer(0.1, lambda: self.run_async(
                self.start_job_execution, restore))
            t.daemon = True
            t.start()
            return self.status
        self.slots = offers
        self.cluster = MiniCluster(
            checkpoint_storage=self.checkpoint_storage,
            checkpoint_interval_ms=self.checkpoint_interval_ms)
        self.status = "RUNNING"

        def run():
            result = self.cluster.execute(self.plan, restore=restore,
                                          timeout_s=600)
            self.run_async(self._job_done, result)

        self._exec_thread = threading.Thread(
            target=run, daemon=True, name=f"jm-exec-{self.job_id}")
        self._exec_thread.start()
        return self.status

    def _job_done(self, result) -> None:
        self.validate_runs_in_main_thread()
        self._stopped = True
        self.result = result
        self.status = result.state
        try:
            rm = self.rpc.connect(self.rm_address)
            await_future(rm.release_slots(self.job_id))
        except ConnectionError:
            pass
        if self.on_finished is not None:
            self.on_finished(self.job_id, result)

    def cancel(self) -> str:
        self.validate_runs_in_main_thread()
        self._stopped = True
        if self.cluster is not None:
            self.cluster.cancel()
        else:
            # never deployed (e.g. still waiting for slots): terminal now
            from flink_tpu.cluster.minicluster import JobResult
            self.status = "CANCELED"
            self._job_done(JobResult(self.job_id, "CANCELED", 0.0))
        return "CANCELLING"

    def trigger_savepoint(self) -> Optional[int]:
        self.validate_runs_in_main_thread()
        return self.cluster.savepoint() if self.cluster is not None else None

    def job_status(self) -> Dict[str, Any]:
        self.validate_runs_in_main_thread()
        base = {"job_id": self.job_id, "status": self.status,
                "slots": len(self.slots)}
        if self.cluster is not None:
            base.update(self.cluster.job_status())
            base["status"] = self.status
        return base


class DispatcherEndpoint(RpcEndpoint):
    """Job submission front door (``Dispatcher.java:100``): persists job
    graphs (HA), spawns one JobMaster per job, recovers on leader start."""

    def __init__(self, rpc: RpcService, rm_address: str,
                 ha_services=None, name: str = "dispatcher",
                 checkpoint_storage_factory: Optional[Callable[[str], Any]] = None,
                 plan_builder: Optional[Callable[[Any], Any]] = None,
                 history_dir: Optional[str] = None):
        super().__init__(name)
        self.rpc = rpc
        self.rm_address = rm_address
        self.ha = ha_services
        self.checkpoint_storage_factory = checkpoint_storage_factory
        #: archive terminal jobs here for the HistoryServer (FsJobArchivist)
        self.history_dir = history_dir
        #: rebuilds an ExecutionPlan from the picklable job spec persisted in
        #: HA (plans themselves hold operator closures — the durable artifact
        #: is the spec, like the reference persists the serialized JobGraph)
        self.plan_builder = plan_builder
        self._ids = itertools.count(1)
        self._jobs: Dict[str, Any] = {}       # job_id -> JobMaster gateway
        self._results: Dict[str, Any] = {}

    def on_start(self) -> None:
        # leader recovery: re-submit persisted, unfinished job graphs
        if self.ha is None:
            return
        if self.plan_builder is None:
            return
        for job_id in self.ha.job_ids():
            payload = self.ha.load_job(job_id)
            if payload is not None and "spec" in payload:
                plan = self.plan_builder(payload["spec"])
                self._spawn(job_id, plan, payload["parallelism"],
                            payload.get("checkpoint_interval_ms", 0),
                            restore_latest=True)

    def submit_job(self, plan, parallelism: int = 1,
                   checkpoint_interval_ms: int = 0,
                   job_spec: Any = None) -> str:
        """``job_spec``: optional PICKLABLE description of the job; with an
        HA store + a dispatcher ``plan_builder`` it makes the job leader-
        failover recoverable (plans themselves contain closures)."""
        self.validate_runs_in_main_thread()
        job_id = f"job-{next(self._ids):04d}"
        if self.ha is not None and job_spec is not None:
            self.ha.persist_job(job_id, {
                "spec": job_spec, "parallelism": parallelism,
                "checkpoint_interval_ms": checkpoint_interval_ms})
        self._spawn(job_id, plan, parallelism, checkpoint_interval_ms)
        return job_id

    def _spawn(self, job_id: str, plan, parallelism: int,
               checkpoint_interval_ms: int, restore_latest: bool = False) -> None:
        storage = (self.checkpoint_storage_factory(job_id)
                   if self.checkpoint_storage_factory else None)
        jm = JobMasterEndpoint(
            job_id, plan, self.rpc, self.rm_address, parallelism,
            checkpoint_storage=storage,
            checkpoint_interval_ms=checkpoint_interval_ms,
            on_finished=self._on_job_finished)
        gw = self.rpc.start_endpoint(jm)
        self._jobs[job_id] = gw
        restore = storage.load_latest() if (restore_latest and storage) else None
        gw.start_job_execution(restore)

    def _on_job_finished(self, job_id: str, result) -> None:
        # called from the JobMaster main thread: marshal into ours
        def record():
            self._results[job_id] = result
            if self.ha is not None and result.state == "FINISHED":
                self.ha.remove_job(job_id)
            if self.history_dir is not None:
                from flink_tpu.rest.history import archive_job
                try:
                    status = await_future(self._jobs[job_id].job_status())
                except Exception:  # noqa: BLE001 — archive the bare result
                    status = {"state": result.state,
                              "error": getattr(result, "error", None)}
                archive_job(self.history_dir, job_id, status)
        self.run_async(record)

    def list_jobs(self) -> List[str]:
        self.validate_runs_in_main_thread()
        return sorted(self._jobs)

    def job_status(self, job_id: str) -> Dict[str, Any]:
        self.validate_runs_in_main_thread()
        gw = self._jobs.get(job_id)
        if gw is None:
            raise KeyError(job_id)
        return await_future(gw.job_status())

    def cancel_job(self, job_id: str) -> str:
        self.validate_runs_in_main_thread()
        return await_future(self._jobs[job_id].cancel())

    def trigger_savepoint(self, job_id: str) -> Optional[int]:
        self.validate_runs_in_main_thread()
        return await_future(self._jobs[job_id].trigger_savepoint())

    def result_of(self, job_id: str):
        self.validate_runs_in_main_thread()
        return self._results.get(job_id)


# ---------------------------------------------------------------------------
# session cluster assembly + client
# ---------------------------------------------------------------------------

class StandaloneSessionCluster:
    """``StandaloneSessionClusterEntrypoint`` analog: RM + Dispatcher + N
    TaskExecutors on one RpcService; optional HA + checkpoint storage."""

    def __init__(self, num_task_executors: int = 1, slots_per_executor: int = 1,
                 ha_services=None,
                 checkpoint_storage_factory: Optional[Callable[[str], Any]] = None,
                 plan_builder: Optional[Callable[[Any], Any]] = None,
                 history_dir: Optional[str] = None):
        self.rpc = RpcService()
        self.rm = ResourceManagerEndpoint(self.rpc)
        self.rm_gw = self.rpc.start_endpoint(self.rm)
        self.task_executors = []
        for i in range(num_task_executors):
            te = TaskExecutorEndpoint(f"taskexecutor-{i}", slots_per_executor)
            self.rpc.start_endpoint(te)
            await_future(self.rm_gw.register_task_executor(te.name))
            self.task_executors.append(te)
        self.dispatcher = DispatcherEndpoint(
            self.rpc, self.rm.name, ha_services=ha_services,
            checkpoint_storage_factory=checkpoint_storage_factory,
            plan_builder=plan_builder, history_dir=history_dir)
        self.dispatcher_gw = self.rpc.start_endpoint(self.dispatcher)

    def client(self) -> "ClusterClient":
        return ClusterClient(self.dispatcher_gw, self.rm_gw)

    def shutdown(self) -> None:
        self.rpc.stop()


class ClusterClient:
    """``RestClusterClient``/CLI-facing client."""

    def __init__(self, dispatcher_gw, rm_gw):
        self._dispatcher = dispatcher_gw
        self._rm = rm_gw

    def submit(self, plan, parallelism: int = 1,
               checkpoint_interval_ms: int = 0, job_spec: Any = None) -> str:
        return await_future(self._dispatcher.submit_job(
            plan, parallelism, checkpoint_interval_ms, job_spec))

    def list_jobs(self) -> List[str]:
        return await_future(self._dispatcher.list_jobs())

    def status(self, job_id: str) -> Dict[str, Any]:
        return await_future(self._dispatcher.job_status(job_id))

    def cancel(self, job_id: str) -> str:
        return await_future(self._dispatcher.cancel_job(job_id))

    def savepoint(self, job_id: str) -> Optional[int]:
        return await_future(self._dispatcher.trigger_savepoint(job_id))

    def overview(self) -> Dict[str, int]:
        return await_future(self._rm.overview())

    def wait_for_completion(self, job_id: str, timeout_s: float = 300.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            res = await_future(self._dispatcher.result_of(job_id))
            if res is not None:
                return res
            st = self.status(job_id)
            if st["status"] in ("FAILED", "CANCELED"):
                time.sleep(0.05)
                return await_future(self._dispatcher.result_of(job_id))
            time.sleep(0.02)
        raise TimeoutError(f"job {job_id} did not complete in {timeout_s}s")
