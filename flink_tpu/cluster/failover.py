"""Restart backoff strategies + pipelined-region computation.

Analogs of ``runtime/executiongraph/failover/flip1/``:
``FixedDelayRestartBackoffTimeStrategy``,
``ExponentialDelayRestartBackoffTimeStrategy``,
``FailureRateRestartBackoffTimeStrategy`` and
``RestartPipelinedRegionFailoverStrategy`` (restart only the connected
pipelined region containing the failed task — here all edges are pipelined,
so a region is a weakly-connected component of the plan).
"""

from __future__ import annotations

import time
from typing import Dict, List, Set

from flink_tpu.graph.stream_graph import ExecutionPlan


class RestartStrategy:
    def can_restart(self) -> bool:
        raise NotImplementedError

    def delay_ms(self) -> int:
        raise NotImplementedError

    def notify_failure(self) -> None:
        """Record one failure occurrence."""


class NoRestartStrategy(RestartStrategy):
    def can_restart(self) -> bool:
        return False

    def delay_ms(self) -> int:
        return 0


class FixedDelayRestartStrategy(RestartStrategy):
    """``fixed-delay``: at most ``attempts`` restarts, constant delay."""

    def __init__(self, attempts: int, delay_ms: int = 50):
        self.attempts = attempts
        self._delay_ms = delay_ms
        self._failures = 0

    def notify_failure(self) -> None:
        self._failures += 1

    def can_restart(self) -> bool:
        return self._failures <= self.attempts

    def delay_ms(self) -> int:
        return self._delay_ms


class ExponentialDelayRestartStrategy(RestartStrategy):
    """``exponential-delay``: backoff doubles per failure up to a cap and
    resets after a quiet period (``ExponentialDelayRestartBackoffTimeStrategy``)."""

    def __init__(self, initial_delay_ms: int = 50, max_delay_ms: int = 10_000,
                 backoff_multiplier: float = 2.0,
                 reset_after_quiet_ms: int = 60_000,
                 max_attempts: int = 1 << 30):
        self.initial_delay_ms = initial_delay_ms
        self.max_delay_ms = max_delay_ms
        self.backoff_multiplier = backoff_multiplier
        self.reset_after_quiet_ms = reset_after_quiet_ms
        self.max_attempts = max_attempts
        self._failures = 0
        self._current_ms = float(initial_delay_ms)
        self._last_failure = 0.0

    def notify_failure(self) -> None:
        now = time.monotonic()
        if self._last_failure and (now - self._last_failure) * 1000 \
                >= self.reset_after_quiet_ms:
            self._current_ms = float(self.initial_delay_ms)
            self._failures = 0
        elif self._failures:
            self._current_ms = min(float(self.max_delay_ms),
                                   self._current_ms * self.backoff_multiplier)
        self._failures += 1
        self._last_failure = now

    def can_restart(self) -> bool:
        return self._failures <= self.max_attempts

    def delay_ms(self) -> int:
        return int(self._current_ms)


class FailureRateRestartStrategy(RestartStrategy):
    """``failure-rate``: give up when more than ``max_failures`` occur within
    ``interval_ms`` (``FailureRateRestartBackoffTimeStrategy``)."""

    def __init__(self, max_failures: int, interval_ms: int,
                 delay_ms: int = 50):
        self.max_failures = max_failures
        self.interval_ms = interval_ms
        self._delay_ms = delay_ms
        self._times: List[float] = []

    def notify_failure(self) -> None:
        now = time.monotonic()
        self._times.append(now)
        cutoff = now - self.interval_ms / 1000.0
        self._times = [t for t in self._times if t >= cutoff]

    def can_restart(self) -> bool:
        return len(self._times) <= self.max_failures

    def delay_ms(self) -> int:
        return self._delay_ms


# ---------------------------------------------------------------------------
# pipelined regions
# ---------------------------------------------------------------------------

def pipelined_regions(plan: ExecutionPlan) -> List[Set[str]]:
    """Weakly-connected components of the plan, as vertex-uid sets
    (``RestartPipelinedRegionFailoverStrategy`` regions: every edge here is
    PIPELINED, so regions are exactly the connected components)."""
    parent: Dict[str, str] = {v.uid: v.uid for v in plan.vertices}

    def find(x: str) -> str:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: str, b: str) -> None:
        parent[find(a)] = find(b)

    for v in plan.vertices:
        for e in v.out_edges:
            union(v.uid, plan.by_id[e.target_id].uid)
    regions: Dict[str, Set[str]] = {}
    for v in plan.vertices:
        regions.setdefault(find(v.uid), set()).add(v.uid)
    return list(regions.values())


def region_of(plan: ExecutionPlan, vertex_uid: str) -> Set[str]:
    for region in pipelined_regions(plan):
        if vertex_uid in region:
            return region
    raise KeyError(vertex_uid)


def subtask_regions(plan: ExecutionPlan,
                    counts: Dict[str, int]) -> "List[Set[tuple]]":
    """Pipelined regions at SUBTASK granularity — the actual
    ``RestartPipelinedRegionFailoverStrategy`` unit: a forward edge at
    equal parallelism connects producer i to consumer i only (so parallel
    forward chains are independent regions); every other partitioning is
    all-to-all and fuses both vertices' subtasks into one region.
    ``counts``: effective subtask count per vertex uid (sources may run one
    subtask per split)."""
    subs = [(v.uid, i) for v in plan.vertices for i in range(counts[v.uid])]
    parent = {s: s for s in subs}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        parent[find(a)] = find(b)

    for v in plan.vertices:
        for e in v.out_edges:
            tgt = plan.by_id[e.target_id]
            np_, nc = counts[v.uid], counts[tgt.uid]
            if e.partitioning == "forward" and np_ == nc:
                for i in range(np_):
                    union((v.uid, i), (tgt.uid, i))
            else:
                for pi in range(np_):
                    for ci in range(nc):
                        union((v.uid, pi), (tgt.uid, ci))
    regions: Dict[tuple, Set[tuple]] = {}
    for s in subs:
        regions.setdefault(find(s), set()).add(s)
    return list(regions.values())
