"""Subtask: one parallel instance of a job vertex, on its own thread.

Analog of ``runtime/taskmanager/Task.java:564`` + the StreamTask mailbox
(``MailboxProcessor.java:66``): a dedicated thread runs a loop whose default
action is polling input channels and whose "mail" is the command queue
(checkpoint triggers, cancel).  All operator mutation happens on this one
thread — the reference's single-writer discipline.

Covers both task flavors:
- **SourceSubtask** (``SourceStreamTask`` analog): drives a split iterator,
  injects checkpoint barriers *between* elements on command (trigger RPC →
  mail, same as the reference's source-task checkpoint trigger, SURVEY §3.4),
  and snapshots its replay offset (element count) — the FLIP-27
  split-state analog for deterministic replayable sources.
- **Subtask**: consumes input channels with per-channel watermark valves
  (``StatusWatermarkValve``) and ALIGNED barrier handling: a channel that
  delivered barrier N stops being polled until every channel delivered N
  (``SingleCheckpointBarrierHandler.processBarrier:194``), then the operator
  snapshot is taken and the barrier forwarded downstream.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, MAX_WATERMARK, CheckpointBarrier,
                                  EndOfInput, LatencyMarker, RecordBatch,
                                  StreamElement, StreamStatus, TaggedBatch,
                                  Watermark)
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.cluster.channels import (LocalChannel, OutputDispatcher,
                                        element_bytes)
from flink_tpu.observability import tracing
from flink_tpu.runtime.executor import WatermarkValve
from flink_tpu.testing import chaos
from flink_tpu.utils import clock
from flink_tpu.utils.clock import MonotoneElapsed


class AlignmentBufferOverflowError(RuntimeError):
    """The blocked-channel alignment queue hit its configured cap
    (``execution.checkpointing.alignment-queue-max-elements``) while
    alignment-timeout escalation is DISABLED: the subtask cannot keep
    buffering barrier-blocked data without growing memory without bound,
    and it cannot escalate to an unaligned checkpoint either.  A loud,
    classified failure beats silent unbounded growth; enable
    ``alignment_timeout_ms`` (or raise the cap) to let the barrier
    overtake instead."""


class TaskStates:
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    CANCELED = "CANCELED"
    FAILED = "FAILED"


class _Cancel(Exception):
    pass


class SubtaskBase:
    #: set by the deploying cluster when incremental checkpointing is on:
    #: periodic checkpoint cuts run inside snapshot_scope(incremental=True)
    #: so delta-tracking operators may ship increments.  Savepoints and
    #: final (FLIP-147) snapshots stay full regardless — they are the
    #: rescale/interchange format
    incremental_checkpoints = False

    def __init__(self, vertex_uid: str, subtask_index: int, operator,
                 outputs: Sequence[OutputDispatcher],
                 ctx: RuntimeContext,
                 listener: "TaskListener"):
        self.vertex_uid = vertex_uid
        self.subtask_index = subtask_index
        self.operator = operator
        self.outputs = list(outputs)
        self.ctx = ctx
        self.listener = listener
        self.commands: "queue.Queue[tuple]" = queue.Queue()
        self.state = TaskStates.DEPLOYING
        self._thread: Optional[threading.Thread] = None
        self._cancelled = threading.Event()
        #: busy/idle/backpressure time accounting (TimerGauge analog,
        #: ``runtime/metrics/TimerGauge.java`` — surfaced by the REST API)
        self.busy_ns = 0
        self.idle_ns = 0
        self.backpressure_ns = 0
        self.records_in = 0
        self.records_out = 0
        #: per-(source, hop) latency recorder (observability/latency.py):
        #: attached by the deploying cluster; every LatencyMarker this
        #: subtask sees records marked_time→now at THIS hop
        self.latency_tracker = None
        #: deploy barrier (threading.Barrier, set by the cluster before
        #: start()): no subtask of one deployment processes input until
        #: EVERY subtask finished open+restore.  Shared-instance sinks
        #: (the collect path) restore by REPLACING their rows; a sibling
        #: appending a fire before the owner subtask's restore ran would
        #: be silently wiped — rescale redeploys hit exactly that race
        self._deploy_gate = None

    # -- lifecycle -----------------------------------------------------------
    def start(self, restore: Optional[Dict[str, Any]] = None) -> None:
        self._restore = restore
        self._thread = threading.Thread(
            target=self._run,
            name=f"task-{self.vertex_uid}-{self.subtask_index}", daemon=True)
        self._thread.start()

    def cancel(self) -> None:
        self._cancelled.set()
        self._abort_deploy_gate()   # a task parked at the barrier must wake
        self.commands.put(("cancel",))
        # Unblock a task thread stuck in a full output channel (backpressure
        # from a dead downstream) or an empty input poll: closed channels
        # refuse puts and wake waiters, so the loop reaches _check_cancel.
        for out in self.outputs:
            for ch in getattr(out, "channels", []):
                ch.close()
        for ch in getattr(self, "inputs", []):
            ch.close()

    def join(self, timeout_s: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- shared plumbing -----------------------------------------------------
    def _emit(self, elements: Sequence[StreamElement]) -> None:
        t0 = time.monotonic_ns()
        for el in elements:
            if isinstance(el, RecordBatch):
                self.records_out += len(el)
            for out in self.outputs:
                out.emit(el)
        # time spent pushing into (possibly full) output channels is
        # backpressure: the reference gauges recordWriter availability
        self.backpressure_ns += time.monotonic_ns() - t0

    def _transition(self, state: str, error: Optional[str] = None) -> None:
        self.state = state
        self.listener.task_state_changed(self.vertex_uid, self.subtask_index,
                                         state, error)

    def _open_and_restore(self) -> None:
        self.operator.open(self.ctx)
        self._opened = True
        if self._restore is not None and self._restore.get("operator") is not None:
            self.operator.restore_state(self._restore["operator"])

    def _check_cancel(self) -> None:
        if self._cancelled.is_set():
            raise _Cancel()

    def _wait_deploy_gate(self) -> None:
        """Hold at the deploy barrier until every sibling subtask finished
        open+restore.  Broken/timed-out barriers (a sibling failed during
        restore, cancel during deploy) degrade to the old
        start-immediately behavior — liveness first."""
        gate = self._deploy_gate
        if gate is None:
            return
        try:
            gate.wait(timeout=30.0)
        except threading.BrokenBarrierError:
            pass

    def _abort_deploy_gate(self) -> None:
        gate = self._deploy_gate
        if gate is not None:
            try:
                gate.abort()
            except Exception:  # noqa: BLE001 — best-effort wakeup
                pass

    def _run(self) -> None:
        try:
            if self._restore is not None and self._restore.get("finished"):
                # restored from a FINAL snapshot (FLIP-147): this task's
                # data is already reflected in every downstream snapshot of
                # the same checkpoint — only the channel-TERMINATION
                # signals must be replayed, or downstream restored tasks
                # would wait forever.  That is BOTH signals the original
                # emitted: the final MAX watermark and EndOfInput.  A
                # downstream subtask restored with a fresh valve (a
                # rescale redeploy) still holds not-yet-fired event-time
                # state; without the watermark those windows would never
                # fire — records silently lost at end of stream.  The
                # watermark is monotone, so downstreams whose valve
                # already saw MAX absorb the duplicate as a no-op.  The
                # state must still be MATERIALIZED in the operator
                # instance: terminal collection (chained collect sinks)
                # reads rows from the live operator, not the snapshot dict
                self.final_snapshot = dict(self._restore)
                self._open_and_restore()
                self._transition(TaskStates.RUNNING)
                self._wait_deploy_gate()
                self._emit([Watermark(MAX_WATERMARK), EndOfInput()])
                self._transition(TaskStates.FINISHED)
                return
            self._open_and_restore()
            self._transition(TaskStates.RUNNING)
            self._wait_deploy_gate()
            self._invoke()
            # FLIP-147 (checkpoints after tasks finish): capture the FINAL
            # state so checkpoints completing after this task ends still
            # contain its contribution — restoring such a checkpoint must
            # not lose finished subtasks' state
            self.final_snapshot = self._final_snapshot()
            self._closed = True   # before close(): a close() that raises
            #                       mid-teardown must not be re-entered below
            self.operator.close()
            self._transition(TaskStates.FINISHED)
        except _Cancel:
            self._abort_deploy_gate()   # siblings must not wait on us
            self._transition(TaskStates.CANCELED)
        except Exception as e:  # noqa: BLE001
            self._abort_deploy_gate()   # a failed restore unblocks siblings
            traceback.print_exc()
            self._transition(TaskStates.FAILED, f"{type(e).__name__}: {e}")
        finally:
            # FAILED/CANCELED tasks must still release operator resources
            # (managed-memory reservations, spill files, sockets): the slot's
            # MemoryManager pool is reused across pipelined-region restarts,
            # so a leaked reservation compounds until reserve_managed fails
            # permanently inside open() (Task.releaseResources in the
            # reference runs on every terminal state, not just FINISHED)
            if getattr(self, "_opened", False) and not getattr(self, "_closed", False):
                try:
                    self.operator.close()
                except Exception:  # noqa: BLE001
                    pass  # teardown best-effort; original failure already reported

    def _invoke(self) -> None:
        raise NotImplementedError

    def _tick_processing_time(self) -> None:
        """Periodic ProcessingTimeService tick on the task thread (the
        reference's timer callbacks run on the mailbox): fires due
        processing-time timers through the operator between elements.
        Rate-limited on RAW monotonic time; the time handed to the
        operator reads through the injectable clock seam and is clamped
        MONOTONE here, so a chaos ``ClockSkew`` backward step can neither
        rewind processing time nor re-fire timers."""
        mono = time.monotonic()
        if mono - getattr(self, "_last_tick_mono", 0.0) < 0.05:
            return
        self._last_tick_mono = mono
        from flink_tpu.utils import clock
        now = max(clock.now_ms(), getattr(self, "_proc_now_ms", 0))
        self._proc_now_ms = now
        out = self.operator.on_processing_time(now)
        if out:
            self._emit(out)

    def _final_snapshot(self) -> Dict[str, Any]:
        return {"operator": self.operator.snapshot_state(), "finished": True}


class SourceSubtask(SubtaskBase):
    """Runs one source split (static deploy) OR a runtime-assigned split
    sequence (FLIP-27 coordination: ``split_requester`` pulls splits from
    the job's ``SourceCoordinator``, the ``RequestSplitEvent`` loop of
    ``SourceCoordinator.java:155-170``); checkpoints replay offsets and the
    in-flight split."""

    def _final_snapshot(self) -> Dict[str, Any]:
        snap = {"operator": self.operator.snapshot_state(),
                "source_offset": self._emitted, "finished": True}
        if self.split_requester is not None:
            # split ownership must survive into checkpoints completed AFTER
            # this reader finished, or restore re-reads its splits
            snap["current_split"] = self._current_split
            snap["finished_splits"] = list(self._finished_splits)
        return snap

    def __init__(self, vertex_uid: str, subtask_index: int, operator,
                 outputs, ctx, listener, split,
                 split_requester=None):
        super().__init__(vertex_uid, subtask_index, operator, outputs, ctx,
                         listener)
        self.split = split
        #: dynamic mode: () -> (split | None, done) — None+not-done means
        #: poll again (the directory may grow)
        self.split_requester = split_requester
        self._emitted = 0          # elements pulled from the current split
        self._current_split = split
        #: dynamic mode: split IDS fully consumed by THIS reader —
        #: snapshotted so a split finished between the enumerator's
        #: trigger-time snapshot and this reader's barrier is still
        #: reclaimed on restore (its records were emitted pre-barrier;
        #: re-reading would duplicate).  Ids, not split objects, and pruned
        #: once a checkpoint containing them COMPLETES (the enumerator's own
        #: snapshot in that checkpoint already covers older assignments), so
        #: snapshot size stays bounded on long-running dynamic sources.
        self._finished_splits: list = []
        self._finished_in_ckpt: Dict[int, int] = {}  # cid -> total at snapshot
        self._finished_total = 0
        self._finished_pruned = 0
        #: stop-with-savepoint: a paused source emits nothing but keeps
        #: serving its command queue (so the savepoint barrier still flows)
        self._paused = threading.Event()
        #: emit a LatencyMarker every N batches (0 = off); the markers ride
        #: the dataflow around user functions (``LatencyMarker.java:32``)
        self.latency_marker_interval = 0
        #: TIME-based emission cadence in ms (0 = off) — what the
        #: ``metrics.latency.interval`` config key wires to; read through
        #: the injectable clock seam so ClockSkew chaos covers latency
        #: tracking like it covers timers.  Batch-based interval wins when
        #: both are set (back-compat with the raw attribute).
        self.latency_marker_interval_ms = 0
        self._last_marker_wall_ms: Optional[int] = None

    def _invoke(self) -> None:
        if self.split_requester is None:
            skip = (self._restore or {}).get("source_offset", 0)
            self._read_split(self.split, skip)
        else:
            restore = self._restore or {}
            cur = restore.get("current_split")
            skip = restore.get("source_offset", 0)
            self._finished_splits = list(restore.get("finished_splits", []))
            self._finished_total = len(self._finished_splits)
            while True:
                if cur is None:
                    self._check_cancel()
                    self._drain_commands()
                    cur, done = self.split_requester()
                    if cur is None:
                        if done:
                            break
                        time.sleep(0.01)   # nothing yet: poll again
                        continue
                    skip = 0
                self._current_split = cur
                self._read_split(cur, skip)
                self._finished_splits.append(self._split_id_of(cur))
                self._finished_total += 1
                self._current_split = cur = None
                self._emitted = 0
        # bounded end: final watermark flushes event-time state downstream
        wm = Watermark(MAX_WATERMARK)
        self._emit(self.operator.process_watermark(wm))
        self._emit([wm])
        self._emit(self.operator.end_input())
        self._emit([EndOfInput()])

    def _read_split(self, split, skip: int) -> None:
        it = iter(split.read())
        for _ in range(skip):      # deterministic replay: skip to the offset
            try:
                next(it)
            except StopIteration:
                break
        self._emitted = skip
        while True:
            self._check_cancel()
            self._drain_commands()
            self._tick_processing_time()
            if self._paused.is_set():
                time.sleep(0.002)  # paused: commands/cancel only
                continue
            try:
                el = next(it)
            except StopIteration:
                break
            self._emitted += 1
            if isinstance(el, RecordBatch):
                # fault point: crash-mid-stream in the source thread (the
                # task FAILs; the restart strategy drives recovery)
                chaos.fire("subtask.run", task=self.vertex_uid,
                           subtask=self.subtask_index)
                self.records_in += len(el)
                self._batches_since_marker = getattr(
                    self, "_batches_since_marker", 0) + 1
                if self._marker_due():
                    self._batches_since_marker = 0
                    # marked_time through the clock seam (not time.time()):
                    # the ClockSkew nemesis must cover latency tracking
                    self._emit([LatencyMarker(clock.now_ms_f() / 1000.0,
                                              subtask_index=self.subtask_index,
                                              source=self.vertex_uid)])
                t0 = time.monotonic_ns()
                out = self.operator.process_batch(el)
                self.busy_ns += time.monotonic_ns() - t0
                self._emit(out)
            elif isinstance(el, Watermark):
                self._emit(self.operator.process_watermark(el))
                if self.operator.forwards_watermarks:
                    self._emit([el])
            else:
                self._emit([el])

    def _marker_due(self) -> bool:
        """Latency-marker cadence: batch-count interval when configured,
        else the wall-clock interval of ``metrics.latency.interval``."""
        if self.latency_marker_interval:
            return (self._batches_since_marker
                    >= self.latency_marker_interval)
        if self.latency_marker_interval_ms:
            now = clock.now_ms()
            last = self._last_marker_wall_ms
            if last is None or now - last >= self.latency_marker_interval_ms \
                    or now < last:          # skew step backward: re-arm
                self._last_marker_wall_ms = now
                return True
        return False

    def _drain_commands(self) -> None:
        while True:
            try:
                cmd = self.commands.get_nowait()
            except queue.Empty:
                return
            if cmd[0] == "checkpoint":
                cid = cmd[1]
                # savepoint barriers stay ALIGNED end-to-end (no barrier
                # overtake, no channel state): the snapshot must remain
                # rescalable/rewritable (drain-then-rescale contract)
                sp = bool(cmd[2]) if len(cmd) > 2 else False
                from flink_tpu.operators.base import snapshot_scope
                try:
                    chaos.fire("subtask.snapshot", task=self.vertex_uid,
                               subtask=self.subtask_index, checkpoint=cid)
                    # drain async emissions downstream BEFORE the barrier
                    prep = getattr(self.operator,
                                   "prepare_snapshot_pre_barrier", None)
                    if prep is not None:
                        self._emit(prep())
                    with tracing.span("checkpoint.snapshot",
                                      cat="checkpoint", checkpoint=cid,
                                      task=self.vertex_uid,
                                      subtask=self.subtask_index), \
                            snapshot_scope(
                                cid, self.incremental_checkpoints
                                and not sp):
                        snap = {"operator": self.operator.snapshot_state(),
                                "source_offset": self._emitted}
                except _Cancel:
                    raise
                except Exception as e:  # noqa: BLE001
                    # snapshot failure DECLINES the checkpoint instead of
                    # killing the task (CheckpointException -> decline);
                    # the barrier still flows so downstream alignment ends
                    self._emit([CheckpointBarrier(cid, timestamp=0,
                                                  is_savepoint=sp)])
                    self.listener.decline_checkpoint(
                        cid, self.vertex_uid, self.subtask_index,
                        f"{type(e).__name__}: {e}")
                    continue
                if self.split_requester is not None:
                    # dynamic mode: the in-flight split AND consumed splits
                    # are reader state (the enumerator's own snapshot can
                    # race assignments made after the trigger)
                    snap["current_split"] = self._current_split
                    snap["finished_splits"] = list(self._finished_splits)
                    self._finished_in_ckpt[cid] = self._finished_total
                barrier = CheckpointBarrier(cid, timestamp=0,
                                            is_savepoint=sp)
                self._emit([barrier])
                self.listener.acknowledge_checkpoint(
                    cid, self.vertex_uid, self.subtask_index, snap)
            elif cmd[0] == "notify_complete":
                self.operator.notify_checkpoint_complete(cmd[1])
                self._prune_finished(cmd[1])
            elif cmd[0] == "cancel":
                raise _Cancel()

    def _split_id_of(self, split) -> str:
        from flink_tpu.connectors.sources import split_id_of
        return split_id_of(split)

    def _prune_finished(self, completed_cid: int) -> None:
        """Drop finished-split ids already covered by a COMPLETED checkpoint:
        a restore from that checkpoint (or any later one) re-marks them via
        the enumerator's own snapshotted assigned-set."""
        covered = [c for c in self._finished_in_ckpt if c <= completed_cid]
        if not covered:
            return
        high = max(self._finished_in_ckpt.pop(c) for c in covered)
        drop = high - self._finished_pruned
        if drop > 0:
            del self._finished_splits[:drop]
            self._finished_pruned = high


class Subtask(SubtaskBase):
    """Channel-consuming subtask with aligned, unaligned, or
    aligned-with-timeout barrier handling.

    Aligned (default): a channel that delivered barrier N stops being
    processed until every channel delivered N
    (``SingleCheckpointBarrierHandler`` semantics) — its post-barrier
    elements buffer in a bounded per-subtask alignment queue
    (``alignment_queue_max`` elements; overflow raises the classified
    :class:`AlignmentBufferOverflowError` when escalation is disabled).

    Unaligned (``unaligned=True`` / ``alignment_timeout_ms=0``): the
    barrier OVERTAKES — on first arrival the operator snapshots and the
    barrier is forwarded immediately; the in-flight elements queued in (or
    still arriving on) not-yet-barriered channels are recorded as
    **channel state** in the snapshot while also being processed; the ack
    happens once every channel delivered the barrier
    (``ChannelStateWriterImpl`` analog).  On restore the recorded elements
    are replayed into the operator BEFORE any new input.

    Aligned-with-timeout (``alignment_timeout_ms > 0``, FLIP-76's
    ``execution.checkpointing.alignment-timeout``): start aligned; once
    alignment exceeds the timeout (measured through the injectable clock
    seam, monotone under ClockSkew) the handler ESCALATES to the unaligned
    path — checkpoint duration stops depending on backpressure.
    """

    def __init__(self, vertex_uid: str, subtask_index: int, operator,
                 outputs, ctx, listener,
                 input_channels: Sequence[LocalChannel],
                 unaligned: bool = False,
                 input_logical: Optional[Sequence[int]] = None,
                 alignment_timeout_ms: Optional[float] = None,
                 alignment_queue_max: int = 8192,
                 input_routing: Optional[Sequence[Dict[str, Any]]] = None):
        super().__init__(vertex_uid, subtask_index, operator, outputs, ctx,
                         listener)
        self.inputs = list(input_channels)
        self.unaligned = unaligned
        #: per-input-channel routing metadata the deploying cluster
        #: captured from the edge ({"partitioning", "key_column",
        #: "max_parallelism", "logical"}): written into the v2
        #: channel-state section so a RESCALE restore can re-route each
        #: persisted in-flight element by the record's own key
        #: (state/redistribute.redistribute_channel_state)
        self.input_routing = ([dict(r) for r in input_routing]
                              if input_routing is not None
                              else [{} for _ in self.inputs])
        #: None = stay aligned forever; 0 = overtake at first arrival
        #: (pure unaligned); >0 = aligned-with-timeout escalation
        self.alignment_timeout_ms = (
            0.0 if unaligned and alignment_timeout_ms is None
            else alignment_timeout_ms)
        self.alignment_queue_max = max(1, int(alignment_queue_max))
        #: physical channel index -> logical input port (two-input operators)
        self.input_logical = (list(input_logical) if input_logical is not None
                              else [0] * len(self.inputs))
        # ---- barrier-handler state (initialized here so job_status() can
        # read the gauges before/while the task thread runs) ----
        self._ended = [False] * len(self.inputs)
        self._barriered: Dict[int, int] = {}   # channel idx -> barrier id
        self._pending_barrier: Optional[CheckpointBarrier] = None
        self._pending_snapshot: Optional[Dict[str, Any]] = None
        self._snapshot_error: Optional[str] = None
        self._overtaken = False                # barrier already overtook
        self._channel_state: List[tuple] = []  # [(input_idx, element), ...]
        self._cs_bytes = 0                     # persisted in-flight bytes
        self._overtaken_bytes = 0
        self._align_queue: List[deque] = [deque()
                                          for _ in range(len(self.inputs))]
        self._align_queued = 0                 # elements across channels
        self._align_timer: Optional[MonotoneElapsed] = None
        #: announcement timer: a barrier QUEUED behind a backlog starts the
        #: clock before the consumer ever drains to it (Flink's priority
        #: barrier announcement); inherited by the alignment timer
        self._announce_timer: Optional[MonotoneElapsed] = None
        self._force_escalate = False
        #: highest barrier id this subtask ever started aligning on: a
        #: LOWER-id barrier finally draining out of a backlog is STALE
        #: (its checkpoint was superseded/expired) and must be dropped,
        #: never allowed to abort a healthy newer alignment
        self._max_barrier_cid = 0
        #: queue-depth gauge peaks: lifetime (the job_status gauge) and
        #: per-alignment (reset at each first barrier — what
        #: last_checkpoint_stats reports, so one historical deep backlog
        #: is never misattributed to later checkpoints)
        self.alignment_queue_peak = 0
        self._align_peak_ckpt = 0
        self.last_checkpoint_stats: Dict[str, Any] = {}

    # ------------------------------------------------------ observability
    @property
    def alignment_queued(self) -> int:
        return self._align_queued

    def channel_stats(self) -> List[Dict[str, Any]]:
        """Per-input-channel backpressure view (monitoring-grade): queue
        depth + bytes and the producer's accumulated credit-wait time."""
        out = []
        for i, ch in enumerate(self.inputs):
            depth_fn = getattr(ch, "depth", None)
            bytes_fn = getattr(ch, "queued_bytes", None)
            out.append({
                "name": getattr(ch, "name", f"in{i}"),
                "depth": int(depth_fn() if depth_fn else len(ch)),
                "queued_bytes": int(bytes_fn()) if bytes_fn else 0,
                "backpressured_ms": round(
                    getattr(ch, "backpressured_ns", 0) / 1e6, 3)})
        return out

    # ------------------------------------------------------------ driving
    def _is_blocked(self, i: int) -> bool:
        """Aligned-phase block: the channel delivered the pending barrier
        and the barrier has not (yet) overtaken."""
        return (self._pending_barrier is not None and not self._overtaken
                and i in self._barriered)

    def _invoke(self) -> None:
        n = len(self.inputs)
        self._valve = WatermarkValve(n)
        # restore the valve FIRST: channel-state replay may carry watermarks
        # (upstream will not resend them), which must advance past the
        # snapshot-time valve, not be clobbered by it
        restored_valve = (self._restore or {}).get("valve")
        if restored_valve is not None:
            self._valve.restore(restored_valve)
        # unaligned restore: replay persisted in-flight channel state into
        # the operator BEFORE any new input (versioned v1 section; legacy
        # bare lists still restore)
        for i, el in self._restored_channel_state():
            self._handle_data(i, el)
        while not all(self._ended):
            self._check_cancel()
            self._drain_commands()
            self._tick_processing_time()
            self._maybe_escalate()
            self._check_announcements()
            progressed = False
            for i, ch in enumerate(self.inputs):
                if self._ended[i]:
                    continue
                el = ch.poll(timeout_s=0.0)
                if el is None:
                    continue
                progressed = True
                if self._is_blocked(i):
                    self._enqueue_aligned(i, el)
                else:
                    self._handle(i, el)
            if not progressed:
                # input momentarily empty: the driver decides this is a
                # pipeline flush point — complete the operator's in-flight
                # hot stages rather than letting results wait on the NEXT
                # batch's arrival (no-op for non-pipelined operators;
                # getattr: duck-typed test operators need not subclass)
                flush = getattr(self.operator, "flush_pipeline", None)
                if flush is not None:
                    self._emit(flush())
                # nothing readable: brief blocking poll on one open channel
                t0 = time.monotonic_ns()
                for i, ch in enumerate(self.inputs):
                    if not self._ended[i] and not self._is_blocked(i):
                        el = ch.poll(timeout_s=0.01)
                        if el is not None:
                            self.idle_ns += time.monotonic_ns() - t0
                            self._handle(i, el)
                        else:
                            self.idle_ns += time.monotonic_ns() - t0
                        break
        self._emit(self.operator.end_input())
        self._emit([EndOfInput()])

    def _restored_channel_state(self) -> List[tuple]:
        cs = (self._restore or {}).get("channel_state")
        if not cs:
            return []
        if isinstance(cs, dict):
            from flink_tpu.state.redistribute import CHANNEL_STATE_VERSIONS
            version = cs.get("version")
            if version not in CHANNEL_STATE_VERSIONS:
                raise ValueError(
                    f"unknown channel-state snapshot version {version!r} "
                    f"(this runtime reads "
                    f"{'/'.join(f'v{v}' for v in CHANNEL_STATE_VERSIONS)})"
                    f" — the checkpoint was written by an incompatible "
                    f"runtime")
            elements = list(cs.get("elements", []))
            if cs.get("by_logical_port"):
                # rescale-redistributed section: elements are keyed by
                # LOGICAL input port (the old physical channel indices
                # died with the old topology) — replay each on the first
                # input channel of its port
                mapped = []
                for port, el in elements:
                    try:
                        i = self.input_logical.index(port)
                    except ValueError:
                        i = 0
                    mapped.append((i, el))
                return mapped
            return elements
        return list(cs)   # legacy: bare [(i, el), ...] list

    def _handle(self, i: int, el: StreamElement) -> None:
        """Single dispatch point for every input element (the mailbox default
        action), including barrier bookkeeping."""
        if isinstance(el, CheckpointBarrier):
            pending = self._pending_barrier
            cid = el.checkpoint_id
            if cid < self._max_barrier_cid:
                # STALE: this barrier's checkpoint was already superseded
                # (it expired while the barrier sat behind a backlog).
                # Its alignment can never complete — every other channel
                # consumed it long ago — so dropping it is the only move
                # that does not abort a HEALTHY newer alignment and
                # cascade spurious declines downstream
                return
            if pending is not None and cid > pending.checkpoint_id:
                # the coordinator gave up on the pending checkpoint (it
                # expired) and triggered a NEWER one: abandon the stale
                # alignment — its recorded channel state belongs to the
                # aborted checkpoint and must not leak into this one
                self._abort_alignment(f"superseded by checkpoint {cid}")
            first = self._pending_barrier is None
            if first:
                tracing.instant("checkpoint.barrier", cat="checkpoint",
                                checkpoint=cid, task=self.vertex_uid,
                                subtask=self.subtask_index)
                self._pending_barrier = el
                self._max_barrier_cid = max(self._max_barrier_cid, cid)
                self._overtaken = False
                self._pending_snapshot = None
                self._snapshot_error = None
                self._channel_state = []
                self._cs_bytes = 0
                self._overtaken_bytes = 0
                self._align_peak_ckpt = 0
                # alignment timer through the injectable clock seam,
                # clamped monotone (ClockSkew must not un-expire it);
                # an announcement that preceded the barrier's arrival
                # already started the clock — alignment time measures
                # from the barrier ENTERING the input, not being drained
                self._align_timer = (self._announce_timer
                                     if self._announce_timer is not None
                                     else MonotoneElapsed())
                self._announce_timer = None
            self._barriered[i] = cid
            if first and not el.is_savepoint \
                    and (self.alignment_timeout_ms == 0
                         or self._force_escalate):
                self._escalate()   # pure unaligned / announced overtake
            self._maybe_complete_alignment()
        elif isinstance(el, EndOfInput):
            self._ended[i] = True
            # a channel ending mid-alignment completes the barrier
            self._maybe_complete_alignment()
        else:
            if (self._pending_barrier is not None and self._overtaken
                    and i not in self._barriered):
                # pre-barrier in-flight data on a not-yet-barriered channel
                # after the overtake: record into channel state AND process
                self._channel_state.append((i, el))
                self._cs_bytes += element_bytes(el)
            self._handle_data(i, el)

    # ------------------------------------------------ alignment machinery
    def _enqueue_aligned(self, i: int, el: StreamElement) -> None:
        """Aligned phase: buffer a blocked channel's post-barrier element.
        The queue is the bounded stand-in for the reference's
        blocked-channel buffer accumulation; its cap either escalates to
        unaligned or fails loudly — never unbounded growth."""
        if self._align_queued >= self.alignment_queue_max:
            barrier = self._pending_barrier
            if barrier is not None and barrier.is_savepoint:
                # a USER-TRIGGERED savepoint must not kill the job: abort
                # just this savepoint (decline + release the buffered
                # elements + forward its barrier) — savepoint() reports
                # None and the job keeps running, memory stays bounded
                self._abort_alignment(
                    f"savepoint {barrier.checkpoint_id} alignment queue "
                    f"overflow ({self._align_queued} elements, cap "
                    f"{self.alignment_queue_max}): savepoints cannot "
                    f"escalate to unaligned — retry once backpressure "
                    f"clears, or raise "
                    f"execution.checkpointing.alignment-queue-max-elements")
                self._process_overtaken(i, el)
                return
            if self.alignment_timeout_ms is not None:
                # cap pressure escalates like timeout expiry does (the
                # size-based escalation of FLIP-182): the overtake drains
                # the queues, then this element processes in FIFO order
                self._escalate()
                self._maybe_complete_alignment()
                self._process_overtaken(i, el)
                return
            msg = (f"alignment queue overflow: {self._align_queued} "
                   f"elements buffered from barrier-blocked channels "
                   f"(cap {self.alignment_queue_max}) while aligning "
                   f"checkpoint "
                   f"{barrier.checkpoint_id if barrier else '?'} and "
                   f"alignment-timeout escalation is disabled — enable "
                   f"execution.checkpointing.alignment-timeout or raise "
                   f"execution.checkpointing.alignment-queue-max-elements")
            if barrier is not None:
                self.listener.decline_checkpoint(
                    barrier.checkpoint_id, self.vertex_uid,
                    self.subtask_index, msg)
            raise AlignmentBufferOverflowError(msg)
        self._align_queue[i].append(el)
        self._align_queued += 1
        self.alignment_queue_peak = max(self.alignment_queue_peak,
                                        self._align_queued)
        self._align_peak_ckpt = max(self._align_peak_ckpt,
                                    self._align_queued)

    def _maybe_escalate(self) -> None:
        """Aligned-with-timeout: escalate once the (monotone, skew-proof)
        alignment timer passes the configured timeout.  SAVEPOINTS never
        escalate: their whole point is a rescalable, rewritable snapshot,
        and channel state is neither (drain-then-rescale contract)."""
        if (self._pending_barrier is None or self._overtaken
                or self._pending_barrier.is_savepoint
                or self.alignment_timeout_ms is None
                or self._align_timer is None):
            return
        if self._align_timer.ms() >= self.alignment_timeout_ms:
            self._escalate()
            self._maybe_complete_alignment()

    def _check_announcements(self) -> None:
        """React to barriers QUEUED behind backlogs (the priority-event
        announcement): before any barrier was drained, an announcement
        starts the alignment clock and — on expiry — the handler jumps the
        queue to the barrier; after an overtake, announced pending-cid
        barriers on laggard channels are extracted the moment they arrive
        instead of waiting for the (backpressured) drain to reach them."""
        if self.alignment_timeout_ms is None:
            return
        if self._pending_barrier is None:
            ann = None
            for i, ch in enumerate(self.inputs):
                if self._ended[i]:
                    continue
                fn = getattr(ch, "announced_barrier", None)
                cid = fn() if fn is not None else None
                if cid is not None:
                    ann = (i, cid)
                    break
            if ann is None:
                self._announce_timer = None
                return
            i, cid = ann
            take = getattr(self.inputs[i], "take_until_barrier", None)
            if take is None:
                return
            if cid < self._max_barrier_cid:
                # a STALE barrier buried in the backlog: extract it so it
                # stops shadowing newer announcements; the elements in
                # front of it are live data, the barrier itself is dropped
                els, _bar = take(cid)
                for el in els:
                    self._process_overtaken(i, el)
                return
            if self._announce_timer is None:
                self._announce_timer = MonotoneElapsed()
            if self._announce_timer.ms() < self.alignment_timeout_ms:
                return
            # announced barrier still buried: extract it — the elements in
            # front of it are PRE-barrier and PRE-snapshot, so they process
            # normally (into the operator snapshot); then the barrier
            # overtakes immediately (savepoint barriers instead START a
            # normal ALIGNED alignment — savepoints never escalate)
            els, bar = take(cid)
            for el in els:
                self._process_overtaken(i, el)
            if bar is not None:
                self._force_escalate = not bar.is_savepoint
                try:
                    self._handle(i, bar)
                finally:
                    self._force_escalate = False
        elif self._overtaken:
            cid = self._pending_barrier.checkpoint_id
            for i, ch in enumerate(self.inputs):
                if self._ended[i] or i in self._barriered:
                    continue
                fn = getattr(ch, "announced_barrier", None)
                acid = fn() if fn is not None else None
                take = getattr(ch, "take_until_barrier", None)
                if acid is None or take is None:
                    continue
                if acid < cid:
                    # stale barrier shadowing the pending one: its
                    # in-front elements are still pre-PENDING-barrier
                    # in-flight data — record them; drop the barrier
                    els, _bar = take(acid)
                else:
                    if acid != cid:
                        continue
                    els, bar = take(cid)
                    if bar is not None:
                        self._barriered[i] = cid
                replay = []
                for el in els:
                    b = element_bytes(el)
                    self._cs_bytes += b
                    self._overtaken_bytes += b
                    self._channel_state.append((i, el))
                    replay.append(el)
                for el in replay:
                    self._process_overtaken(i, el)
            self._maybe_complete_alignment()

    def _escalate(self) -> None:
        """The barrier OVERTAKES: snapshot now, forward now, extract the
        in-flight elements queued in front of not-yet-delivered barriers
        into channel state, and unblock the aligned queues."""
        barrier = self._pending_barrier
        if barrier is None or self._overtaken:
            return
        cid = barrier.checkpoint_id
        from flink_tpu.operators.base import snapshot_scope
        try:
            chaos.fire("subtask.snapshot", task=self.vertex_uid,
                       subtask=self.subtask_index, checkpoint=cid)
            prep = getattr(self.operator,
                           "prepare_snapshot_pre_barrier", None)
            if prep is not None:
                self._emit(prep())
            with tracing.span("checkpoint.snapshot", cat="checkpoint",
                              checkpoint=cid, task=self.vertex_uid,
                              subtask=self.subtask_index, overtake=True), \
                    snapshot_scope(cid, self.incremental_checkpoints
                                   and not barrier.is_savepoint):
                self._pending_snapshot = {
                    "operator": self.operator.snapshot_state(),
                    "valve": self._valve.snapshot()}
        except _Cancel:
            raise
        except Exception as e:  # noqa: BLE001
            # decline at alignment completion (barrier still flows)
            self._pending_snapshot = None
            self._snapshot_error = f"{type(e).__name__}: {e}"
        self._emit([barrier])
        self._overtaken = True
        replay: List[tuple] = []
        overtaken = 0
        # in-flight data the barrier jumps over: everything queued in
        # front of the barrier on not-yet-barriered channels is CHANNEL
        # STATE (persisted + processed); if the barrier itself is queued,
        # the channel counts as delivered without waiting for the
        # (backpressured) consumer to drain to it
        for i, ch in enumerate(self.inputs):
            if self._ended[i] or i in self._barriered:
                continue
            take = getattr(ch, "take_until_barrier", None)
            if take is None:
                continue
            els, bar = take(cid)
            for el in els:
                b = element_bytes(el)
                overtaken += b
                self._cs_bytes += b
                self._channel_state.append((i, el))
                replay.append((i, el))
            if bar is not None:
                self._barriered[i] = cid
        # unblock the aligned queues: their buffered elements are
        # POST-barrier data on already-delivered channels — overtaken by
        # the barrier, processed now, NOT part of the snapshot
        for i, q in enumerate(self._align_queue):
            while q:
                el = q.popleft()
                overtaken += element_bytes(el)
                replay.append((i, el))
        self._align_queued = 0
        self._overtaken_bytes += overtaken
        for i, el in replay:
            self._process_overtaken(i, el)

    def _process_overtaken(self, i: int, el: StreamElement) -> None:
        """Process an element released by an overtake/abort drain.  Data
        was already recorded into channel state where required, so it must
        NOT go back through ``_handle``'s recording path; barriers and
        end-of-input keep their full bookkeeping, and a NEW alignment
        started mid-drain re-blocks its channels."""
        if isinstance(el, (CheckpointBarrier, EndOfInput)):
            self._handle(i, el)
        elif self._is_blocked(i):
            self._enqueue_aligned(i, el)
        else:
            self._handle_data(i, el)

    def _abort_alignment(self, reason: str) -> None:
        """A superseding barrier invalidated the pending checkpoint: drop
        its recorded channel state, decline it (the coordinator already
        expired it — late declines are ignored), release the buffered
        elements, and make sure downstream alignment for it still ends."""
        barrier = self._pending_barrier
        if barrier is None:
            return
        cid = barrier.checkpoint_id
        was_overtaken = self._overtaken
        self._pending_barrier = None
        self._pending_snapshot = None
        self._snapshot_error = None
        self._overtaken = False
        self._channel_state = []
        self._cs_bytes = 0
        self._barriered.clear()
        self._align_timer = None
        queued: List[tuple] = []
        for i, q in enumerate(self._align_queue):
            while q:
                queued.append((i, q.popleft()))
        self._align_queued = 0
        for i, el in queued:
            self._process_overtaken(i, el)
        if not was_overtaken:
            # never forwarded: downstream alignment must still end
            self._emit([barrier])
        self.listener.decline_checkpoint(cid, self.vertex_uid,
                                         self.subtask_index, reason)

    def _emit_status_change(self, st) -> None:
        if st is not None:
            self._emit([StreamStatus(st)])

    def _handle_data(self, i: int, el: StreamElement) -> None:
        if isinstance(el, Watermark):
            self._emit_status_change(self._valve.record_activity(i))
            adv = self._valve.input_watermark(i, el.timestamp)
            if adv is not None:
                wm = Watermark(adv)
                self._emit(self.operator.process_watermark(wm))
                if self.operator.forwards_watermarks:
                    self._emit([wm])
        elif isinstance(el, StreamStatus):
            # idleness: drop the channel from the min; that alone can
            # advance event time (StatusWatermarkValve.markIdle)
            adv, combined, changed = self._valve.status_update(i, el.idle)
            if adv is not None:
                wm = Watermark(adv)
                self._emit(self.operator.process_watermark(wm))
                if self.operator.forwards_watermarks:
                    self._emit([wm])
            if changed:   # forward the SUBTASK's combined status, on change
                self._emit([StreamStatus(combined)])
        elif isinstance(el, TaggedBatch):
            if getattr(self.operator, "accepts_tag", None) == el.tag:
                self._emit(self.operator.process_tagged(el.batch))
        elif isinstance(el, RecordBatch):
            if len(el):
                # fault point: crash mid-stream in a consuming subtask
                chaos.fire("subtask.run", task=self.vertex_uid,
                           subtask=self.subtask_index)
                self._emit_status_change(self._valve.record_activity(i))
                self.records_in += len(el)
                t0 = time.monotonic_ns()
                if getattr(self.operator, "is_two_input", False):
                    out = self.operator.process_batch2(
                        el, self.input_logical[i])
                else:
                    out = self.operator.process_batch(el)
                self.busy_ns += time.monotonic_ns() - t0
                self._emit(out)
        elif isinstance(el, LatencyMarker):
            # LatencyMarker flows around user functions; sinks record it.
            # The hook may return elements to keep forwarding (chains).
            if self.latency_tracker is not None:
                # record marked_time→now at THIS hop: the sink hop's
                # histogram is the end-to-end latency, intermediate hops
                # decompose it per operator
                self.latency_tracker.record(el, self.vertex_uid)
            hook = getattr(self.operator, "on_latency_marker", None)
            if hook is not None:
                out = hook(el)
                if out:
                    self._emit(list(out))
            else:
                self._emit([el])
        else:
            self._emit([el])

    def _maybe_complete_alignment(self) -> None:
        if self._pending_barrier is None:
            return
        if not all(self._ended[j] or j in self._barriered
                   for j in range(len(self.inputs))):
            return
        barrier = self._pending_barrier
        self._take_checkpoint(barrier)
        self._barriered.clear()
        self._pending_barrier = None
        self._align_timer = None
        # aligned completion: the blocked channels' buffered post-barrier
        # elements process now, BEFORE any new poll of those channels
        # (overtaken completions drained them at escalation already)
        queued: List[tuple] = []
        for i, q in enumerate(self._align_queue):
            while q:
                queued.append((i, q.popleft()))
        self._align_queued = 0
        for i, el in queued:
            self._process_overtaken(i, el)

    def _record_checkpoint_stats(self, cid: int, align_ms: float,
                                 unaligned: bool, persisted: int) -> None:
        tracing.instant("checkpoint.alignment", cat="checkpoint",
                        checkpoint=cid, task=self.vertex_uid,
                        subtask=self.subtask_index,
                        alignment_ms=round(align_ms, 3),
                        unaligned=unaligned)
        self.last_checkpoint_stats = {
            "checkpoint_id": cid,
            "alignment_ms": round(align_ms, 3),
            "unaligned": unaligned,
            "overtaken_bytes": self._overtaken_bytes,
            "persisted_inflight_bytes": persisted,
            "alignment_queue_peak": self._align_peak_ckpt}

    def _take_checkpoint(self, barrier: CheckpointBarrier) -> None:
        cid = barrier.checkpoint_id
        align_ms = self._align_timer.ms() if self._align_timer else 0.0
        if self._overtaken:
            if self._pending_snapshot is None:
                # overtake-time snapshot failed: decline now that every
                # channel delivered the barrier (the recorded channel
                # state belongs to the aborted checkpoint — drop it)
                self._channel_state = []
                self._cs_bytes = 0
                self._record_checkpoint_stats(cid, align_ms, True, 0)
                self.listener.decline_checkpoint(
                    cid, self.vertex_uid, self.subtask_index,
                    self._snapshot_error or "snapshot failed")
                return
            snap = self._pending_snapshot
            # versioned channel-state section: the persisted in-flight
            # elements plus the overtake accounting.  v2 adds the
            # per-input routing metadata (key column / partitioning /
            # producer max-parallelism / logical port) that rescale-time
            # redistribution routes persisted elements by
            snap["channel_state"] = {
                "version": 2,
                "elements": list(self._channel_state),
                "inputs": [dict(r) for r in self.input_routing],
                "persisted_bytes": self._cs_bytes,
                "overtaken_bytes": self._overtaken_bytes,
                "alignment_ms": round(align_ms, 3),
                "unaligned": True}
            self._record_checkpoint_stats(cid, align_ms, True,
                                          self._cs_bytes)
            self._pending_snapshot = None
            self._channel_state = []
            self._cs_bytes = 0
            # barrier was already forwarded at the overtake
        else:
            from flink_tpu.operators.base import snapshot_scope
            try:
                chaos.fire("subtask.snapshot", task=self.vertex_uid,
                           subtask=self.subtask_index, checkpoint=cid)
                prep = getattr(self.operator,
                               "prepare_snapshot_pre_barrier", None)
                if prep is not None:
                    self._emit(prep())
                with tracing.span("checkpoint.snapshot", cat="checkpoint",
                                  checkpoint=cid, task=self.vertex_uid,
                                  subtask=self.subtask_index), \
                        snapshot_scope(cid, self.incremental_checkpoints
                                       and not barrier.is_savepoint):
                    snap = {"operator": self.operator.snapshot_state(),
                            "valve": self._valve.snapshot()}
            except _Cancel:
                raise
            except Exception as e:  # noqa: BLE001
                self._emit([barrier])   # downstream alignment must end
                self._record_checkpoint_stats(cid, align_ms, False, 0)
                self.listener.decline_checkpoint(
                    cid, self.vertex_uid, self.subtask_index,
                    f"{type(e).__name__}: {e}")
                return
            snap["channel_state"] = {
                "version": 2, "elements": [],
                "inputs": [dict(r) for r in self.input_routing],
                "persisted_bytes": 0, "overtaken_bytes": 0,
                "alignment_ms": round(align_ms, 3), "unaligned": False}
            self._record_checkpoint_stats(cid, align_ms, False, 0)
            self._emit([barrier])
        self.listener.acknowledge_checkpoint(
            cid, self.vertex_uid, self.subtask_index, snap)

    def _drain_commands(self) -> None:
        while True:
            try:
                cmd = self.commands.get_nowait()
            except queue.Empty:
                return
            if cmd[0] == "notify_complete":
                self.operator.notify_checkpoint_complete(cmd[1])
            elif cmd[0] == "cancel":
                raise _Cancel()


def aggregate_channel_state(snapshots) -> Dict[str, Any]:
    """Roll up the subtask acks' channel-state (v1) sections for one
    completed checkpoint — shared by both coordinators so the schema has
    exactly one reader: max alignment across subtasks (the checkpoint's
    critical path), summed overtaken / persisted in-flight bytes, and
    whether ANY subtask's barrier overtook."""
    align_ms = 0.0
    overtaken = persisted = 0
    any_unaligned = False
    for snap in snapshots:
        cs = snap.get("channel_state") if isinstance(snap, dict) else None
        if isinstance(cs, dict):
            align_ms = max(align_ms, cs.get("alignment_ms", 0.0))
            overtaken += cs.get("overtaken_bytes", 0)
            persisted += cs.get("persisted_bytes", 0)
            any_unaligned |= bool(cs.get("unaligned"))
    return {"alignment_ms": round(align_ms, 3),
            "overtaken_bytes": overtaken,
            "persisted_inflight_bytes": persisted,
            "unaligned": any_unaligned}


class TaskListener:
    """Callbacks from subtask threads to the coordination layer."""

    def task_state_changed(self, vertex_uid: str, subtask_index: int,
                           state: str, error: Optional[str]) -> None:
        pass

    def acknowledge_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                               subtask_index: int,
                               snapshot: Dict[str, Any]) -> None:
        pass

    def decline_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                           subtask_index: int, error: str) -> None:
        """A task could not snapshot (``declineCheckpoint`` RPC analog):
        the coordinator aborts the pending checkpoint and charges it to
        the CheckpointFailureManager's tolerable budget."""
