"""Subtask: one parallel instance of a job vertex, on its own thread.

Analog of ``runtime/taskmanager/Task.java:564`` + the StreamTask mailbox
(``MailboxProcessor.java:66``): a dedicated thread runs a loop whose default
action is polling input channels and whose "mail" is the command queue
(checkpoint triggers, cancel).  All operator mutation happens on this one
thread — the reference's single-writer discipline.

Covers both task flavors:
- **SourceSubtask** (``SourceStreamTask`` analog): drives a split iterator,
  injects checkpoint barriers *between* elements on command (trigger RPC →
  mail, same as the reference's source-task checkpoint trigger, SURVEY §3.4),
  and snapshots its replay offset (element count) — the FLIP-27
  split-state analog for deterministic replayable sources.
- **Subtask**: consumes input channels with per-channel watermark valves
  (``StatusWatermarkValve``) and ALIGNED barrier handling: a channel that
  delivered barrier N stops being polled until every channel delivered N
  (``SingleCheckpointBarrierHandler.processBarrier:194``), then the operator
  snapshot is taken and the barrier forwarded downstream.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, MAX_WATERMARK, CheckpointBarrier,
                                  EndOfInput, LatencyMarker, RecordBatch,
                                  StreamElement, StreamStatus, TaggedBatch,
                                  Watermark)
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.cluster.channels import LocalChannel, OutputDispatcher
from flink_tpu.runtime.executor import WatermarkValve
from flink_tpu.testing import chaos


class TaskStates:
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    CANCELED = "CANCELED"
    FAILED = "FAILED"


class _Cancel(Exception):
    pass


class SubtaskBase:
    def __init__(self, vertex_uid: str, subtask_index: int, operator,
                 outputs: Sequence[OutputDispatcher],
                 ctx: RuntimeContext,
                 listener: "TaskListener"):
        self.vertex_uid = vertex_uid
        self.subtask_index = subtask_index
        self.operator = operator
        self.outputs = list(outputs)
        self.ctx = ctx
        self.listener = listener
        self.commands: "queue.Queue[tuple]" = queue.Queue()
        self.state = TaskStates.DEPLOYING
        self._thread: Optional[threading.Thread] = None
        self._cancelled = threading.Event()
        #: busy/idle/backpressure time accounting (TimerGauge analog,
        #: ``runtime/metrics/TimerGauge.java`` — surfaced by the REST API)
        self.busy_ns = 0
        self.idle_ns = 0
        self.backpressure_ns = 0
        self.records_in = 0
        self.records_out = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self, restore: Optional[Dict[str, Any]] = None) -> None:
        self._restore = restore
        self._thread = threading.Thread(
            target=self._run,
            name=f"task-{self.vertex_uid}-{self.subtask_index}", daemon=True)
        self._thread.start()

    def cancel(self) -> None:
        self._cancelled.set()
        self.commands.put(("cancel",))
        # Unblock a task thread stuck in a full output channel (backpressure
        # from a dead downstream) or an empty input poll: closed channels
        # refuse puts and wake waiters, so the loop reaches _check_cancel.
        for out in self.outputs:
            for ch in getattr(out, "channels", []):
                ch.close()
        for ch in getattr(self, "inputs", []):
            ch.close()

    def join(self, timeout_s: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- shared plumbing -----------------------------------------------------
    def _emit(self, elements: Sequence[StreamElement]) -> None:
        t0 = time.monotonic_ns()
        for el in elements:
            if isinstance(el, RecordBatch):
                self.records_out += len(el)
            for out in self.outputs:
                out.emit(el)
        # time spent pushing into (possibly full) output channels is
        # backpressure: the reference gauges recordWriter availability
        self.backpressure_ns += time.monotonic_ns() - t0

    def _transition(self, state: str, error: Optional[str] = None) -> None:
        self.state = state
        self.listener.task_state_changed(self.vertex_uid, self.subtask_index,
                                         state, error)

    def _open_and_restore(self) -> None:
        self.operator.open(self.ctx)
        self._opened = True
        if self._restore is not None and self._restore.get("operator") is not None:
            self.operator.restore_state(self._restore["operator"])

    def _check_cancel(self) -> None:
        if self._cancelled.is_set():
            raise _Cancel()

    def _run(self) -> None:
        try:
            if self._restore is not None and self._restore.get("finished"):
                # restored from a FINAL snapshot (FLIP-147): this task's
                # data and end-of-input effects are already reflected in
                # every downstream snapshot of the same checkpoint — only
                # the channel-termination signal must be replayed, or
                # downstream restored tasks would wait forever.  The state
                # must still be MATERIALIZED in the operator instance:
                # terminal collection (chained collect sinks) reads rows
                # from the live operator, not from the snapshot dict
                self.final_snapshot = dict(self._restore)
                self._open_and_restore()
                self._transition(TaskStates.RUNNING)
                self._emit([EndOfInput()])
                self._transition(TaskStates.FINISHED)
                return
            self._open_and_restore()
            self._transition(TaskStates.RUNNING)
            self._invoke()
            # FLIP-147 (checkpoints after tasks finish): capture the FINAL
            # state so checkpoints completing after this task ends still
            # contain its contribution — restoring such a checkpoint must
            # not lose finished subtasks' state
            self.final_snapshot = self._final_snapshot()
            self._closed = True   # before close(): a close() that raises
            #                       mid-teardown must not be re-entered below
            self.operator.close()
            self._transition(TaskStates.FINISHED)
        except _Cancel:
            self._transition(TaskStates.CANCELED)
        except Exception as e:  # noqa: BLE001
            traceback.print_exc()
            self._transition(TaskStates.FAILED, f"{type(e).__name__}: {e}")
        finally:
            # FAILED/CANCELED tasks must still release operator resources
            # (managed-memory reservations, spill files, sockets): the slot's
            # MemoryManager pool is reused across pipelined-region restarts,
            # so a leaked reservation compounds until reserve_managed fails
            # permanently inside open() (Task.releaseResources in the
            # reference runs on every terminal state, not just FINISHED)
            if getattr(self, "_opened", False) and not getattr(self, "_closed", False):
                try:
                    self.operator.close()
                except Exception:  # noqa: BLE001
                    pass  # teardown best-effort; original failure already reported

    def _invoke(self) -> None:
        raise NotImplementedError

    def _tick_processing_time(self) -> None:
        """Periodic ProcessingTimeService tick on the task thread (the
        reference's timer callbacks run on the mailbox): fires due
        processing-time timers through the operator between elements.
        Rate-limited on RAW monotonic time; the time handed to the
        operator reads through the injectable clock seam and is clamped
        MONOTONE here, so a chaos ``ClockSkew`` backward step can neither
        rewind processing time nor re-fire timers."""
        mono = time.monotonic()
        if mono - getattr(self, "_last_tick_mono", 0.0) < 0.05:
            return
        self._last_tick_mono = mono
        from flink_tpu.utils import clock
        now = max(clock.now_ms(), getattr(self, "_proc_now_ms", 0))
        self._proc_now_ms = now
        out = self.operator.on_processing_time(now)
        if out:
            self._emit(out)

    def _final_snapshot(self) -> Dict[str, Any]:
        return {"operator": self.operator.snapshot_state(), "finished": True}


class SourceSubtask(SubtaskBase):
    """Runs one source split (static deploy) OR a runtime-assigned split
    sequence (FLIP-27 coordination: ``split_requester`` pulls splits from
    the job's ``SourceCoordinator``, the ``RequestSplitEvent`` loop of
    ``SourceCoordinator.java:155-170``); checkpoints replay offsets and the
    in-flight split."""

    def _final_snapshot(self) -> Dict[str, Any]:
        snap = {"operator": self.operator.snapshot_state(),
                "source_offset": self._emitted, "finished": True}
        if self.split_requester is not None:
            # split ownership must survive into checkpoints completed AFTER
            # this reader finished, or restore re-reads its splits
            snap["current_split"] = self._current_split
            snap["finished_splits"] = list(self._finished_splits)
        return snap

    def __init__(self, vertex_uid: str, subtask_index: int, operator,
                 outputs, ctx, listener, split,
                 split_requester=None):
        super().__init__(vertex_uid, subtask_index, operator, outputs, ctx,
                         listener)
        self.split = split
        #: dynamic mode: () -> (split | None, done) — None+not-done means
        #: poll again (the directory may grow)
        self.split_requester = split_requester
        self._emitted = 0          # elements pulled from the current split
        self._current_split = split
        #: dynamic mode: split IDS fully consumed by THIS reader —
        #: snapshotted so a split finished between the enumerator's
        #: trigger-time snapshot and this reader's barrier is still
        #: reclaimed on restore (its records were emitted pre-barrier;
        #: re-reading would duplicate).  Ids, not split objects, and pruned
        #: once a checkpoint containing them COMPLETES (the enumerator's own
        #: snapshot in that checkpoint already covers older assignments), so
        #: snapshot size stays bounded on long-running dynamic sources.
        self._finished_splits: list = []
        self._finished_in_ckpt: Dict[int, int] = {}  # cid -> total at snapshot
        self._finished_total = 0
        self._finished_pruned = 0
        #: stop-with-savepoint: a paused source emits nothing but keeps
        #: serving its command queue (so the savepoint barrier still flows)
        self._paused = threading.Event()
        #: emit a LatencyMarker every N batches (0 = off); the markers ride
        #: the dataflow around user functions (``LatencyMarker.java:32``)
        self.latency_marker_interval = 0

    def _invoke(self) -> None:
        if self.split_requester is None:
            skip = (self._restore or {}).get("source_offset", 0)
            self._read_split(self.split, skip)
        else:
            restore = self._restore or {}
            cur = restore.get("current_split")
            skip = restore.get("source_offset", 0)
            self._finished_splits = list(restore.get("finished_splits", []))
            self._finished_total = len(self._finished_splits)
            while True:
                if cur is None:
                    self._check_cancel()
                    self._drain_commands()
                    cur, done = self.split_requester()
                    if cur is None:
                        if done:
                            break
                        time.sleep(0.01)   # nothing yet: poll again
                        continue
                    skip = 0
                self._current_split = cur
                self._read_split(cur, skip)
                self._finished_splits.append(self._split_id_of(cur))
                self._finished_total += 1
                self._current_split = cur = None
                self._emitted = 0
        # bounded end: final watermark flushes event-time state downstream
        wm = Watermark(MAX_WATERMARK)
        self._emit(self.operator.process_watermark(wm))
        self._emit([wm])
        self._emit(self.operator.end_input())
        self._emit([EndOfInput()])

    def _read_split(self, split, skip: int) -> None:
        it = iter(split.read())
        for _ in range(skip):      # deterministic replay: skip to the offset
            try:
                next(it)
            except StopIteration:
                break
        self._emitted = skip
        while True:
            self._check_cancel()
            self._drain_commands()
            self._tick_processing_time()
            if self._paused.is_set():
                time.sleep(0.002)  # paused: commands/cancel only
                continue
            try:
                el = next(it)
            except StopIteration:
                break
            self._emitted += 1
            if isinstance(el, RecordBatch):
                # fault point: crash-mid-stream in the source thread (the
                # task FAILs; the restart strategy drives recovery)
                chaos.fire("subtask.run", task=self.vertex_uid,
                           subtask=self.subtask_index)
                self.records_in += len(el)
                self._batches_since_marker = getattr(
                    self, "_batches_since_marker", 0) + 1
                if self.latency_marker_interval and \
                        self._batches_since_marker >= self.latency_marker_interval:
                    self._batches_since_marker = 0
                    self._emit([LatencyMarker(time.time(),
                                              subtask_index=self.subtask_index)])
                t0 = time.monotonic_ns()
                out = self.operator.process_batch(el)
                self.busy_ns += time.monotonic_ns() - t0
                self._emit(out)
            elif isinstance(el, Watermark):
                self._emit(self.operator.process_watermark(el))
                if self.operator.forwards_watermarks:
                    self._emit([el])
            else:
                self._emit([el])

    def _drain_commands(self) -> None:
        while True:
            try:
                cmd = self.commands.get_nowait()
            except queue.Empty:
                return
            if cmd[0] == "checkpoint":
                cid = cmd[1]
                from flink_tpu.operators.base import snapshot_scope
                try:
                    chaos.fire("subtask.snapshot", task=self.vertex_uid,
                               subtask=self.subtask_index, checkpoint=cid)
                    # drain async emissions downstream BEFORE the barrier
                    prep = getattr(self.operator,
                                   "prepare_snapshot_pre_barrier", None)
                    if prep is not None:
                        self._emit(prep())
                    with snapshot_scope(cid):
                        snap = {"operator": self.operator.snapshot_state(),
                                "source_offset": self._emitted}
                except _Cancel:
                    raise
                except Exception as e:  # noqa: BLE001
                    # snapshot failure DECLINES the checkpoint instead of
                    # killing the task (CheckpointException -> decline);
                    # the barrier still flows so downstream alignment ends
                    self._emit([CheckpointBarrier(cid, timestamp=0)])
                    self.listener.decline_checkpoint(
                        cid, self.vertex_uid, self.subtask_index,
                        f"{type(e).__name__}: {e}")
                    continue
                if self.split_requester is not None:
                    # dynamic mode: the in-flight split AND consumed splits
                    # are reader state (the enumerator's own snapshot can
                    # race assignments made after the trigger)
                    snap["current_split"] = self._current_split
                    snap["finished_splits"] = list(self._finished_splits)
                    self._finished_in_ckpt[cid] = self._finished_total
                barrier = CheckpointBarrier(cid, timestamp=0)
                self._emit([barrier])
                self.listener.acknowledge_checkpoint(
                    cid, self.vertex_uid, self.subtask_index, snap)
            elif cmd[0] == "notify_complete":
                self.operator.notify_checkpoint_complete(cmd[1])
                self._prune_finished(cmd[1])
            elif cmd[0] == "cancel":
                raise _Cancel()

    def _split_id_of(self, split) -> str:
        from flink_tpu.connectors.sources import split_id_of
        return split_id_of(split)

    def _prune_finished(self, completed_cid: int) -> None:
        """Drop finished-split ids already covered by a COMPLETED checkpoint:
        a restore from that checkpoint (or any later one) re-marks them via
        the enumerator's own snapshotted assigned-set."""
        covered = [c for c in self._finished_in_ckpt if c <= completed_cid]
        if not covered:
            return
        high = max(self._finished_in_ckpt.pop(c) for c in covered)
        drop = high - self._finished_pruned
        if drop > 0:
            del self._finished_splits[:drop]
            self._finished_pruned = high


class Subtask(SubtaskBase):
    """Channel-consuming subtask with aligned OR unaligned barriers.

    Aligned (default): a channel that delivered barrier N stops being polled
    until every channel delivered N; snapshot at full alignment
    (``SingleCheckpointBarrierHandler`` semantics).

    Unaligned (``unaligned=True``): the barrier overtakes — on FIRST arrival
    the operator snapshots and the barrier is forwarded immediately; elements
    still arriving on not-yet-barriered channels keep being processed but are
    ALSO recorded as **channel state** in the snapshot; the ack happens once
    every channel delivered the barrier (``ChannelStateWriterImpl`` analog).
    On restore the recorded elements are re-processed first.
    """

    def __init__(self, vertex_uid: str, subtask_index: int, operator,
                 outputs, ctx, listener,
                 input_channels: Sequence[LocalChannel],
                 unaligned: bool = False,
                 input_logical: Optional[Sequence[int]] = None):
        super().__init__(vertex_uid, subtask_index, operator, outputs, ctx,
                         listener)
        self.inputs = list(input_channels)
        self.unaligned = unaligned
        #: physical channel index -> logical input port (two-input operators)
        self.input_logical = (list(input_logical) if input_logical is not None
                              else [0] * len(self.inputs))

    def _invoke(self) -> None:
        n = len(self.inputs)
        self._valve = WatermarkValve(n)
        self._ended = [False] * n
        self._blocked: Dict[int, int] = {}  # channel idx -> blocking barrier id
        self._pending_barrier: Optional[CheckpointBarrier] = None
        self._pending_snapshot: Optional[Dict[str, Any]] = None
        self._channel_state: List[tuple] = []   # [(input_idx, element), ...]
        # restore the valve FIRST: channel-state replay may carry watermarks
        # (upstream will not resend them), which must advance past the
        # snapshot-time valve, not be clobbered by it
        restored_valve = (self._restore or {}).get("valve")
        if restored_valve is not None:
            self._valve.restore(restored_valve)
        # unaligned restore: re-process recorded in-flight elements
        for i, el in (self._restore or {}).get("channel_state", []):
            self._handle_data(i, el)
        while not all(self._ended):
            self._check_cancel()
            self._drain_commands()
            self._tick_processing_time()
            progressed = False
            for i, ch in enumerate(self.inputs):
                if self._ended[i] or i in self._blocked:
                    continue
                el = ch.poll(timeout_s=0.0)
                if el is None:
                    continue
                progressed = True
                self._handle(i, el)
            if not progressed:
                # input momentarily empty: the driver decides this is a
                # pipeline flush point — complete the operator's in-flight
                # hot stages rather than letting results wait on the NEXT
                # batch's arrival (no-op for non-pipelined operators;
                # getattr: duck-typed test operators need not subclass)
                flush = getattr(self.operator, "flush_pipeline", None)
                if flush is not None:
                    self._emit(flush())
                # nothing readable: brief blocking poll on one open channel
                t0 = time.monotonic_ns()
                for i, ch in enumerate(self.inputs):
                    if not self._ended[i] and i not in self._blocked:
                        el = ch.poll(timeout_s=0.01)
                        if el is not None:
                            self.idle_ns += time.monotonic_ns() - t0
                            self._handle(i, el)
                        else:
                            self.idle_ns += time.monotonic_ns() - t0
                        break
        self._emit(self.operator.end_input())
        self._emit([EndOfInput()])

    def _handle(self, i: int, el: StreamElement) -> None:
        """Single dispatch point for every input element (the mailbox default
        action), including barrier bookkeeping."""
        if isinstance(el, CheckpointBarrier):
            first = self._pending_barrier is None
            self._blocked[i] = el.checkpoint_id
            self._pending_barrier = el
            if self.unaligned and first:
                # barrier overtakes: snapshot NOW, forward NOW
                from flink_tpu.operators.base import snapshot_scope
                try:
                    chaos.fire("subtask.snapshot", task=self.vertex_uid,
                               subtask=self.subtask_index,
                               checkpoint=el.checkpoint_id)
                    prep = getattr(self.operator,
                                   "prepare_snapshot_pre_barrier", None)
                    if prep is not None:
                        self._emit(prep())
                    with snapshot_scope(el.checkpoint_id):
                        self._pending_snapshot = {
                            "operator": self.operator.snapshot_state(),
                            "valve": self._valve.snapshot()}
                except _Cancel:
                    raise
                except Exception as e:  # noqa: BLE001
                    # decline at alignment completion (barrier still flows)
                    self._pending_snapshot = None
                    self._snapshot_error = f"{type(e).__name__}: {e}"
                self._emit([el])
            self._maybe_complete_alignment()
        elif isinstance(el, EndOfInput):
            self._ended[i] = True
            # a channel ending mid-alignment completes the barrier
            self._maybe_complete_alignment()
        else:
            if self.unaligned and self._pending_barrier is not None:
                # pre-barrier in-flight data on a not-yet-barriered channel:
                # record into channel state AND process normally
                self._channel_state.append((i, el))
            self._handle_data(i, el)

    def _emit_status_change(self, st) -> None:
        if st is not None:
            self._emit([StreamStatus(st)])

    def _handle_data(self, i: int, el: StreamElement) -> None:
        if isinstance(el, Watermark):
            self._emit_status_change(self._valve.record_activity(i))
            adv = self._valve.input_watermark(i, el.timestamp)
            if adv is not None:
                wm = Watermark(adv)
                self._emit(self.operator.process_watermark(wm))
                if self.operator.forwards_watermarks:
                    self._emit([wm])
        elif isinstance(el, StreamStatus):
            # idleness: drop the channel from the min; that alone can
            # advance event time (StatusWatermarkValve.markIdle)
            adv, combined, changed = self._valve.status_update(i, el.idle)
            if adv is not None:
                wm = Watermark(adv)
                self._emit(self.operator.process_watermark(wm))
                if self.operator.forwards_watermarks:
                    self._emit([wm])
            if changed:   # forward the SUBTASK's combined status, on change
                self._emit([StreamStatus(combined)])
        elif isinstance(el, TaggedBatch):
            if getattr(self.operator, "accepts_tag", None) == el.tag:
                self._emit(self.operator.process_tagged(el.batch))
        elif isinstance(el, RecordBatch):
            if len(el):
                # fault point: crash mid-stream in a consuming subtask
                chaos.fire("subtask.run", task=self.vertex_uid,
                           subtask=self.subtask_index)
                self._emit_status_change(self._valve.record_activity(i))
                self.records_in += len(el)
                t0 = time.monotonic_ns()
                if getattr(self.operator, "is_two_input", False):
                    out = self.operator.process_batch2(
                        el, self.input_logical[i])
                else:
                    out = self.operator.process_batch(el)
                self.busy_ns += time.monotonic_ns() - t0
                self._emit(out)
        elif isinstance(el, LatencyMarker):
            # LatencyMarker flows around user functions; sinks record it.
            # The hook may return elements to keep forwarding (chains).
            hook = getattr(self.operator, "on_latency_marker", None)
            if hook is not None:
                out = hook(el)
                if out:
                    self._emit(list(out))
            else:
                self._emit([el])
        else:
            self._emit([el])

    def _maybe_complete_alignment(self) -> None:
        if self._pending_barrier is None:
            return
        if all(self._ended[j] or j in self._blocked
               for j in range(len(self.inputs))):
            self._take_checkpoint(self._pending_barrier)
            self._blocked.clear()
            self._pending_barrier = None

    def _take_checkpoint(self, barrier: CheckpointBarrier) -> None:
        cid = barrier.checkpoint_id
        if self.unaligned:
            if self._pending_snapshot is None:
                # first-arrival snapshot failed: decline now that every
                # channel delivered the barrier (the recorded channel
                # state belongs to the aborted checkpoint — drop it)
                self._channel_state = []
                self.listener.decline_checkpoint(
                    cid, self.vertex_uid, self.subtask_index,
                    getattr(self, "_snapshot_error", "snapshot failed"))
                return
            snap = self._pending_snapshot
            snap["channel_state"] = list(self._channel_state)
            self._pending_snapshot = None
            self._channel_state = []
            # barrier was already forwarded at first arrival
        else:
            from flink_tpu.operators.base import snapshot_scope
            try:
                chaos.fire("subtask.snapshot", task=self.vertex_uid,
                           subtask=self.subtask_index, checkpoint=cid)
                prep = getattr(self.operator,
                               "prepare_snapshot_pre_barrier", None)
                if prep is not None:
                    self._emit(prep())
                with snapshot_scope(cid):
                    snap = {"operator": self.operator.snapshot_state(),
                            "valve": self._valve.snapshot()}
            except _Cancel:
                raise
            except Exception as e:  # noqa: BLE001
                self._emit([barrier])   # downstream alignment must end
                self.listener.decline_checkpoint(
                    cid, self.vertex_uid, self.subtask_index,
                    f"{type(e).__name__}: {e}")
                return
            self._emit([barrier])
        self.listener.acknowledge_checkpoint(
            cid, self.vertex_uid, self.subtask_index, snap)

    def _drain_commands(self) -> None:
        while True:
            try:
                cmd = self.commands.get_nowait()
            except queue.Empty:
                return
            if cmd[0] == "notify_complete":
                self.operator.notify_checkpoint_complete(cmd[1])
            elif cmd[0] == "cancel":
                raise _Cancel()


class TaskListener:
    """Callbacks from subtask threads to the coordination layer."""

    def task_state_changed(self, vertex_uid: str, subtask_index: int,
                           state: str, error: Optional[str]) -> None:
        pass

    def acknowledge_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                               subtask_index: int,
                               snapshot: Dict[str, Any]) -> None:
        pass

    def decline_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                           subtask_index: int, error: str) -> None:
        """A task could not snapshot (``declineCheckpoint`` RPC analog):
        the coordinator aborts the pending checkpoint and charges it to
        the CheckpointFailureManager's tolerable budget."""
