"""Injectable process clock — the single seam between the runtime and
wall/monotonic time.

Every time-dependent runtime component (processing-time ticks in the
executors and task drivers, state TTL in ``state/heap.py`` and
``state/spill.py``, session-gap closing, heartbeat liveness) reads time
through this module instead of calling ``time.time()`` directly, for two
reasons:

1. **Chaos**: an installed :class:`~flink_tpu.testing.chaos.ClockSkew`
   schedule (points ``clock.wall`` / ``clock.monotonic``) offsets every
   reading deterministically — seeded backward steps, forward jumps and
   drift, the NTP-misbehaviour nemesis.  Consumers must therefore never
   assume two consecutive readings are ordered; components that need
   monotone time clamp at their own boundary (the executors' processing
   -time tick, ``InternalTimerService.advance_processing_time``).
2. **Tests**: a :class:`Clock` instance is injectable wherever a component
   takes a ``clock=`` parameter, without monkeypatching ``time``.

The chaos hook costs one module attribute read + ``None`` check when no
injector is installed (``chaos.skew``), so the hot paths can afford it.
"""

from __future__ import annotations

import time

from flink_tpu.testing import chaos

__all__ = ["Clock", "SYSTEM_CLOCK", "now_ms", "now_ms_f", "monotonic",
           "MonotoneElapsed", "sleep"]


class Clock:
    """Wall + monotonic clock pair, chaos-overridable per reading."""

    def now_ms(self) -> int:
        """Wall clock in epoch milliseconds (``clock.wall`` skew point)."""
        return int(time.time() * 1000.0 + chaos.skew("clock.wall"))

    def now_ms_f(self) -> float:
        """Wall clock in epoch milliseconds WITHOUT the int truncation,
        same ``clock.wall`` skew point.  Latency tracking needs sub-ms
        resolution (hops routinely complete in <1 ms — quantized
        endpoints would record every such sample as 0), but must still
        sit behind the chaos seam like every other wall reading."""
        return time.time() * 1000.0 + chaos.skew("clock.wall")

    def monotonic(self) -> float:
        """Monotonic seconds (``clock.monotonic`` skew point, offset in
        ms).  NOTE: under an active skew schedule this is no longer
        monotone — that is the point of the nemesis."""
        return time.monotonic() + chaos.skew("clock.monotonic") / 1000.0


class MonotoneElapsed:
    """Elapsed-seconds tracker that stays MONOTONE under a skewed
    monotonic clock (chaos ``ClockSkew`` on ``clock.monotonic``).

    Checkpoint expiry and alignment timers measure *elapsed* time; under a
    backward clock step a naive ``now - start`` shrinks, which would
    un-expire an already-expired checkpoint (or push an alignment timeout
    into the future forever while the nemesis oscillates).  Readings here
    clamp at their own high-water mark, so expiry decisions never regress:
    once a deadline is passed it stays passed, matching the reference's
    monotone ``ProcessingTimeService`` contract for its checkpoint
    timeouts."""

    def __init__(self, clock: "Clock" = None):
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._start = self._clock.monotonic()
        self._hw = 0.0

    def seconds(self) -> float:
        self._hw = max(self._hw, self._clock.monotonic() - self._start)
        return self._hw

    def ms(self) -> float:
        return self.seconds() * 1000.0


SYSTEM_CLOCK = Clock()


def now_ms() -> int:
    return SYSTEM_CLOCK.now_ms()


def now_ms_f() -> float:
    return SYSTEM_CLOCK.now_ms_f()


def monotonic() -> float:
    return SYSTEM_CLOCK.monotonic()


def sleep(seconds: float) -> None:
    """Pacing sleep — a raw ``time.sleep`` passthrough for poll-loop
    cadence.  Deliberately NOT skewed: chaos targets time *decisions*
    (deadlines, cooldowns, expiry — all of which must read
    :class:`MonotoneElapsed` / the skewed readings above), not the OS
    scheduler.  Living here keeps seam consumers off ``import time``
    entirely, so a stray ``time.time()`` decision can't sneak back in."""
    time.sleep(seconds)
