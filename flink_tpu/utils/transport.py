"""Transport calibration: measured cost of keeping a device replica in sync.

Some deployments put the accelerator behind a *taxed* transport — e.g. a
tunneled/proxied device where executing a jitted update step costs the HOST
tens of CPU-ms per uploaded MB (protocol serialization on the dispatch
path), stealing the very core the operator's native kernels run on
(measured here: a fused C probe that takes ~5ms solo takes ~13ms while
dispatched device work is in flight).  On such links, per-record device
syncs cost more host CPU than the entire rest of the pipeline; on healthy
links (direct PCIe/ICI, or the CPU backend where the "device" is the host
itself) they are ~free.

Operators that can run host-authoritative (the window operator's host emit
tier, ``operators/window_agg.py``) consult this module to pick a device
sync cadence: per-record ``scatter`` on healthy links, ``deferred``
(replica refreshed at sync points — barriers, idle, end of input) on taxed
ones.  This is the ingress-side twin of the round-3 egress finding that
fire-time downloads are transport-forbidden on tunnel links (PARITY.md
"emit tier").

Calibration is *self-measured*, not synthetic: a plain blocking
``device_put`` does NOT expose the tax (the tunnel streams raw buffers at
~GB/s; the cost is in executing dispatched computations), so the operator
records the until-ready wall time of its own first few real update steps
via :func:`record_dispatch_cost` and this module aggregates the verdict
process-wide (the link does not change under a running process — later
operators skip the probe entirely).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

#: dispatch cost above this marks the link taxed.  Tunnel transports
#: measure ~25-40 ms/MB; direct-attached accelerators < 1 ms/MB.  The CPU
#: backend calibrates too: there the "transport" is the XLA dispatch
#: compute itself (a CPU scatter costs ~0.5µs/update regardless of state
#: size), which on slow hosts measures far past this threshold — exactly
#: the boxes where per-batch replica sync loses to the deferred refresh.
DISPATCH_TAXED_ABOVE_MS_PER_MB = 6.0

#: samples needed before a verdict; the MIN per-MB cost is used, so the
#: first sample's compile time and queue-drain noise cannot tip the scale
MIN_SAMPLES = 3

#: samples below this upload size are discarded: a healthy link's FIXED
#: dispatch latency (~0.2-1 ms) divided by a sub-MB payload reads as a
#: huge per-MB cost and would freeze a false "taxed" verdict process-wide.
#: Tiny-batch workloads therefore never calibrate and keep the safe
#: default (per-batch scatter).
MIN_SAMPLE_MB = 0.5

_samples: List[Tuple[float, float]] = []  # (mb, seconds)
_verdict: Optional[bool] = None


def record_dispatch_cost(mb: float, seconds: float) -> None:
    """Feed one measured (uploaded MB, until-ready seconds) sample from a
    real dispatched update step.  Sub-``MIN_SAMPLE_MB`` samples are ignored
    (fixed dispatch latency would masquerade as per-MB cost)."""
    global _verdict
    if mb < MIN_SAMPLE_MB:
        return
    _samples.append((mb, seconds))
    if _verdict is None and len(_samples) >= MIN_SAMPLES:
        best = min(s / m for m, s in _samples)
        _verdict = best * 1e3 > DISPATCH_TAXED_ABOVE_MS_PER_MB


def dispatch_taxed() -> Optional[bool]:
    """True/False once calibrated; None while samples are still needed."""
    return _verdict


def dispatch_ms_per_mb() -> Optional[float]:
    """Best measured dispatch cost in ms per uploaded MB (None = unmeasured)."""
    if not _samples:
        return None
    return min(s / m for m, s in _samples) * 1e3


def reset(verdict: Optional[bool] = None) -> None:
    """Clear calibration state (tests), optionally pinning a verdict."""
    global _samples, _verdict
    _samples = []
    _verdict = verdict
