"""JAX platform selection helper.

The TPU-tunnel site hook (sitecustomize → register) overrides jax's
platform choice via ``jax.config.update("jax_platforms", ...)`` at
interpreter start, so the ``JAX_PLATFORMS`` environment variable alone is
not enough to keep a process off the one shared real chip.  Every
entrypoint that must honor the env var (CLI workers spawned from a
CPU-forced test context, the bench's CPU smoke mode, tests/conftest.py)
calls this once before touching any jax API.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Re-assert ``JAX_PLATFORMS`` over any site-hook override; a missing
    or broken jax leaves the process untouched (CLI subcommands that never
    use jax must still work)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax

        jax.config.update("jax_platforms", plat)
    except Exception:  # noqa: BLE001
        pass
