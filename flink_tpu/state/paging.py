"""Cold-key paging: the HBM pane ring as a cache over an unbounded key space.

The reference grows keyed state past memory by swapping the heap backend for
RocksDB (``RocksDBAggregatingState.java:45``, SURVEY §7.3 "state larger than
HBM"); the device pane ring of
:class:`~flink_tpu.operators.window_agg.WindowAggOperator` gets the same
capability from a **residency tier**: the ``[K_cap, P, *leaf]`` ring holds
only the HOT keys, and cold keys' pane cells live serialized in the
memory-budgeted native :class:`~flink_tpu.native.SpillStore` (which itself
overflows to disk) — key cardinality is no longer capped by HBM.

Split of labor:

- :class:`DevicePager` (here) owns every HOST-side decision: the residency
  map (global key id -> HBM row), victim selection (clock second-chance or
  exact LRU), the per-pane spilled-key bitmaps, and the serialized
  per-(key, pane) entries in a :class:`~flink_tpu.state.spill.PaneSpillStore`
  (count + emit-mirror bit + leaf values in device dtypes — eviction and
  promotion round-trip bit-exactly).
- The operator owns every DEVICE dispatch: one batched gather for the
  evicted rows' live-pane cells (page-out), one batched reset+set for the
  promoted rows (page-in), and one combine+get_result over uploaded columns
  when spilled keys participate in a window fire.  Paging cost per
  micro-batch is a handful of gather/scatter dispatches, never per-key host
  chatter.

Invariant: every (key, pane) cell lives in EXACTLY one tier.  Promotion
folds a key's spilled cells back into its fresh HBM row (and deletes the
entries) before the batch's scatter touches the row, so a promoted key's
accumulation history is identical to an always-resident key's — the basis of
the fire-digest-equality acceptance tests.

Spilled keys stay first-class: they participate in window fires (the
operator uploads their columns and runs the same pane combine), in
snapshots (``fill_snapshot`` merges them into the repo-standard dense keyed
snapshot format, so ``redistribute.split_keyed_snapshot`` and rescale work
unchanged), and in restore at any K_cap (``import_rows`` spills the
overflow).
"""

from __future__ import annotations

import numpy as np
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from flink_tpu.observability import tracing

#: flags bit: the (key, pane) cell was marked in the host emit mirror
MIRROR_BIT = 1

#: rows examined per clock-sweep chunk (vectorized second-chance scan)
_CLOCK_CHUNK = 4096


def identity_grid(spec, rows: int, cols: int) -> List[np.ndarray]:
    """One ``[rows, cols, *leaf]`` array per ACC leaf, filled with the
    accumulator identity in DEVICE dtypes — the shared cell-grid layout of
    page-in columns, spilled fires and dense snapshots."""
    out = []
    for init, shape, dt in zip(spec.leaf_inits, spec.leaf_shapes,
                               spec.leaf_dtypes):
        arr = np.empty((rows, cols) + tuple(shape), dt)
        arr[...] = np.asarray(init).astype(dt)
        out.append(arr)
    return out


@dataclass
class PagingConfig:
    """Operator-facing paging knobs (``docs/operations.md`` "State larger
    than HBM").

    capacity:   resident key capacity K_cap (rounded up to a power of two
                by the operator) — the HBM footprint stays ``K_cap * P``
                cells regardless of key cardinality.
    policy:     "clock" (second-chance ref bits, O(1) amortized) or "lru"
                (exact least-recently-touched via access ticks).
    directory:  spill directory for the native store's disk log (a fresh
                temp dir when None).
    mem_budget: resident-byte budget of the SpillStore before IT evicts
                entries to its disk log.
    """

    capacity: int
    policy: str = "clock"
    directory: Optional[str] = None
    mem_budget: int = 64 << 20


class DevicePager:
    """Host-side residency manager for one operator's pane ring."""

    def __init__(self, config: PagingConfig, spec, capacity: int):
        if config.policy not in ("clock", "lru"):
            raise ValueError(f"paging policy must be clock|lru, "
                             f"got {config.policy!r}")
        if config.capacity <= 0:
            raise ValueError("paging capacity must be positive")
        from flink_tpu.state.spill import PaneSpillStore

        self.config = config
        self.spec = spec
        self.K = int(capacity)
        self.store = PaneSpillStore(config.directory, config.mem_budget,
                                    spec.leaf_dtypes, spec.leaf_shapes)
        #: lifetime counters (metrics: paging.evictions / paging.promotions)
        self.evictions = 0
        self.promotions = 0
        self._reset_maps()

    def _reset_maps(self) -> None:
        #: global key id -> HBM row, -1 = not resident (grows with keys)
        self.row_of = np.full(1024, -1, np.int32)
        #: HBM row -> global key id, -1 = free
        self.gid_of = np.full(self.K, -1, np.int64)
        self._tick = np.zeros(self.K, np.int64)   # lru: last-touch stamp
        self._ref = np.zeros(self.K, np.uint8)    # clock: second-chance bit
        self._hand = 0
        self._clock = 0
        self._n_resident = 0
        self._next_free = 0                       # fresh rows low-water mark
        self._free: List[int] = []                # rows recycled by eviction
        #: pane id -> bool[num_keys] "this key has a spilled cell here"
        self.spilled: Dict[int, np.ndarray] = {}

    def reset(self) -> None:
        """Drop all residency + spilled state (operator ``reset_state``)."""
        self.store.clear()
        self._reset_maps()
        self.evictions = 0
        self.promotions = 0

    def close(self) -> None:
        self.store.close()

    # -- residency map ------------------------------------------------------
    def ensure_gids(self, n: int) -> None:
        if n > self.row_of.size:
            grown = np.full(max(n, self.row_of.size * 2), -1, np.int32)
            grown[: self.row_of.size] = self.row_of
            self.row_of = grown

    def rows(self, gids: np.ndarray) -> np.ndarray:
        return self.row_of[gids]

    @property
    def resident_keys(self) -> int:
        return self._n_resident

    @property
    def row_high_water(self) -> int:
        """Rows ever assigned (fresh low-water mark): bounds live rows."""
        return self._next_free

    def free_count(self) -> int:
        return (self.K - self._next_free) + len(self._free)

    def touch(self, rows: np.ndarray) -> None:
        self._clock += 1
        self._tick[rows] = self._clock
        self._ref[rows] = 1

    def resident_pairs(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, gids) of every assigned row, ascending row order."""
        rows = np.flatnonzero(self.gid_of >= 0)
        return rows.astype(np.int32), self.gid_of[rows]

    # -- victim selection ---------------------------------------------------
    def pick_victims(self, n: int, protected_rows: np.ndarray) -> np.ndarray:
        """``n`` cold resident rows to evict; never rows of keys in the
        current batch (``protected_rows``) — their cells are about to be
        scattered into."""
        elig = self.gid_of >= 0
        if protected_rows.size:
            elig[protected_rows] = False
        if int(np.count_nonzero(elig)) < n:
            raise RuntimeError(
                f"paging: batch working set exceeds capacity (need {n} "
                f"victims, {int(np.count_nonzero(elig))} eligible of "
                f"K_cap={self.K}) — shrink the batch or raise capacity")
        if self.config.policy == "lru":
            cand = np.flatnonzero(elig)
            if n >= cand.size:
                return cand.astype(np.int32)
            pick = cand[np.argpartition(self._tick[cand], n - 1)[:n]]
            return pick.astype(np.int32)
        # clock: vectorized second-chance sweep.  Two full sweeps clear
        # every ref bit, so the bound below always terminates with picks.
        out = np.empty(n, np.int64)
        filled = 0
        chunks_per_sweep = (self.K + _CLOCK_CHUNK - 1) // _CLOCK_CHUNK
        for _ in range(3 * chunks_per_sweep + 1):
            idx = (self._hand + np.arange(min(_CLOCK_CHUNK, self.K))) % self.K
            self._hand = int((self._hand + idx.size) % self.K)
            cand = idx[elig[idx]]
            if cand.size == 0:
                continue
            second = self._ref[cand] == 1
            self._ref[cand[second]] = 0   # second chance spent
            pick = cand[~second]
            take = min(n - filled, pick.size)
            out[filled: filled + take] = pick[:take]
            elig[pick[:take]] = False
            filled += take
            if filled >= n:
                break
        if filled < n:          # pathological interleaving: force-complete
            rest = np.flatnonzero(elig)[: n - filled]
            out[filled: filled + rest.size] = rest
            filled += rest.size
        return out[:n].astype(np.int32)

    # -- page-out / page-in -------------------------------------------------
    def spill_rows(self, victim_rows: np.ndarray, panes: np.ndarray,
                   counts: np.ndarray, leaves: List[np.ndarray],
                   mirror_bits: np.ndarray) -> None:
        """Serialize the victims' live-pane cells (downloaded by the
        operator) into the store and free their rows.  ``counts`` is
        ``[V, m]`` int, ``leaves`` one ``[V, m, *leaf]`` array per ACC leaf,
        ``mirror_bits`` ``[V, m]`` bool."""
        with tracing.span("paging.page_out", cat="paging",
                          keys=int(victim_rows.size),
                          panes=int(np.asarray(panes).size)):
            gids = self.gid_of[victim_rows]
            pl = [int(p) for p in np.asarray(panes).tolist()]
            for i, g in enumerate(gids.tolist()):
                for j, p in enumerate(pl):
                    c = int(counts[i, j])
                    b = bool(mirror_bits[i, j])
                    if c or b:
                        self.store.put(g, p, MIRROR_BIT if b else 0, c,
                                       [l[i, j] for l in leaves])
                        self._mark_spilled(p, g)
            self.row_of[gids] = -1
            self.gid_of[victim_rows] = -1
            self._ref[victim_rows] = 0
            self._free.extend(int(r) for r in victim_rows.tolist())
            self._n_resident -= int(victim_rows.size)
            self.evictions += int(victim_rows.size)

    def assign_rows(self, gids: np.ndarray) -> Tuple[np.ndarray, int]:
        """Bind free rows to ``gids`` (promotion/new keys); returns
        (rows int32, n_recycled) — recycled rows carry stale device cells
        the operator must reset before use."""
        need = int(gids.size)
        rows = np.empty(need, np.int64)
        fresh = min(need, self.K - self._next_free)
        if fresh:
            rows[:fresh] = np.arange(self._next_free, self._next_free + fresh)
            self._next_free += fresh
        recycled = need - fresh
        for i in range(recycled):
            rows[fresh + i] = self._free.pop()
        self.row_of[gids] = rows
        self.gid_of[rows] = gids
        self._n_resident += need
        self.touch(rows)
        return rows.astype(np.int32), recycled

    def load_entries(self, gids: np.ndarray, panes: np.ndarray,
                     delete: bool):
        """Dense ``[R, m]`` columns of the spilled cells of ``gids`` over
        ``panes`` (identity where nothing is spilled): (counts int32,
        leaves in device dtypes, mirror bits, found bool[R]).  With
        ``delete`` the entries move OUT of the spill tier (promotion) and
        the promotion counter advances."""
        R, m = int(gids.size), int(np.asarray(panes).size)
        tracing.instant("paging.page_in", cat="paging", keys=R, panes=m,
                        promote=bool(delete))
        counts = np.zeros((R, m), np.int32)
        bits = np.zeros((R, m), bool)
        leaves = identity_grid(self.spec, R, m)
        found = np.zeros(R, bool)
        gl = np.asarray(gids).tolist()
        for j, p in enumerate(np.asarray(panes).tolist()):
            mark = self.spilled.get(int(p))
            if mark is None:
                continue
            for i, g in enumerate(gl):
                if g >= mark.size or not mark[g]:
                    continue
                entry = self.store.get(g, int(p))
                if entry is None:
                    continue
                flags, c, vals = entry
                counts[i, j] = c
                bits[i, j] = bool(flags & MIRROR_BIT) or c > 0
                for k, v in enumerate(vals):
                    leaves[k][i, j] = v
                found[i] = True
                if delete:
                    self.store.delete(g, int(p))
                    mark[g] = False
        if delete:
            self.promotions += int(found.sum())
        return counts, leaves, bits, found

    # -- spilled-key queries -------------------------------------------------
    def any_spilled(self, gids: np.ndarray, panes: np.ndarray) -> bool:
        """Cheap pre-check: does ANY of ``gids`` hold a spilled cell in any
        of ``panes``?  Saves the dense load_entries grids on the dominant
        all-new-keys batches while the key space is still growing."""
        gids = np.asarray(gids)
        for p in np.asarray(panes).tolist():
            mark = self.spilled.get(int(p))
            if mark is None:
                continue
            sub = gids[gids < mark.size]
            if sub.size and mark[sub].any():
                return True
        return False

    def _mark_spilled(self, pane: int, gid: int) -> None:
        arr = self.spilled.get(pane)
        if arr is None or arr.size <= gid:
            grown = np.zeros(max(self.row_of.size, gid + 1), bool)
            if arr is not None:
                grown[: arr.size] = arr
            arr = self.spilled[pane] = grown
        arr[gid] = True

    def spilled_gids(self, panes: np.ndarray) -> np.ndarray:
        """Ascending global ids holding a spilled cell in any of ``panes``."""
        acc: Optional[np.ndarray] = None
        for p in np.asarray(panes).tolist():
            mark = self.spilled.get(int(p))
            if mark is None:
                continue
            if acc is None:
                acc = mark.copy()
            else:
                if acc.size < mark.size:
                    acc = np.pad(acc, (0, mark.size - acc.size))
                acc[: mark.size] |= mark
        if acc is None:
            return np.empty(0, np.int64)
        return np.flatnonzero(acc).astype(np.int64)

    def drop_panes(self, panes) -> None:
        """Pane expiry: delete every spilled cell of the expired panes."""
        for p in panes:
            mark = self.spilled.pop(int(p), None)
            if mark is None:
                continue
            for g in np.flatnonzero(mark).tolist():
                self.store.delete(g, int(p))

    # -- snapshot / restore ---------------------------------------------------
    def fill_snapshot(self, counts: np.ndarray, leaves: List[np.ndarray],
                      panes: np.ndarray) -> None:
        """Merge spilled cells into dense gid-indexed snapshot arrays
        (``counts [n, m]``, one ``[n, m, *leaf]`` per leaf) — the
        repo-standard keyed snapshot format, redistribute-compatible."""
        for j, p in enumerate(np.asarray(panes).tolist()):
            mark = self.spilled.get(int(p))
            if mark is None:
                continue
            for g in np.flatnonzero(mark).tolist():
                entry = self.store.get(g, int(p))
                if entry is None:
                    continue
                _flags, c, vals = entry
                counts[g, j] = c
                for k, v in enumerate(vals):
                    leaves[k][g, j] = v

    def import_rows(self, gids: np.ndarray, panes: np.ndarray,
                    counts: np.ndarray, leaves: List[np.ndarray]) -> None:
        """Restore overflow: spill snapshot rows (gid-indexed dense arrays)
        that do not fit the resident capacity."""
        pl = [int(p) for p in np.asarray(panes).tolist()]
        for g in np.asarray(gids).tolist():
            for j, p in enumerate(pl):
                c = int(counts[g, j])
                if not c:
                    continue
                self.store.put(g, p, MIRROR_BIT, c,
                               [l[g, j] for l in leaves])
                self._mark_spilled(p, g)

    # -- observability --------------------------------------------------------
    def stats(self, num_keys: int) -> Dict[str, int]:
        """Occupancy + lifetime counters (metrics: ``paging.*``)."""
        return {
            "resident_keys": int(self._n_resident),
            "spilled_keys": int(max(0, num_keys - self._n_resident)),
            "evictions": int(self.evictions),
            "promotions": int(self.promotions),
            "capacity": int(self.K),
            "spill_mem_bytes": int(self.store.mem_used()),
            "spill_log_bytes": int(self.store.log_bytes()),
        }
