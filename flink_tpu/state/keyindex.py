"""Key -> dense-slot index: sparse record keys to dense HBM state rows.

The reference stores keyed state in hash maps probed per record
(``CopyOnWriteStateMap.java``); device state here is a dense ``[K, ...]``
array, so the host must map each record's key to a stable dense row id.  This
is the batched analog of that hash probe: a **vectorized open-addressing
table** (numpy, no per-record Python) for int64 keys, and a
factorize+dictionary variant for object (string) keys.  Slot ids are stable
for the life of the operator (until snapshot/rescale), are dense (0..n-1,
growing), and double as row indices into the device accumulator arrays.

When the native layer is available (``native/flink_native.cc`` keydict), the
int64 table delegates to a C++ open-addressing dict — one ctypes call per
micro-batch instead of numpy probe rounds (~8x faster at 1M keys); the
numpy implementation remains the portable fallback, and both speak the same
snapshot format.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — avalanching hash for table probing."""
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def _keydict_lib():
    """The native lib iff it exposes the keydict symbols."""
    from flink_tpu.native import get_lib

    lib = get_lib()
    if lib is not None and hasattr(lib, "keydict_create"):
        return lib
    return None


class KeyIndex:
    """Vectorized int64-key -> dense int32 slot table (open addressing).

    Delegates to the C++ keydict when the native layer is built; otherwise
    runs the numpy probe rounds.  Identical snapshots either way."""

    def __init__(self, initial_capacity: int = 1 << 16, max_load: float = 0.5):
        self._lib = _keydict_lib()
        self._handle = None
        self._max_load = max_load
        self._n = 0
        if self._lib is not None:
            self._handle = self._lib.keydict_create(int(initial_capacity))
            return
        cap = 1
        while cap < initial_capacity:
            cap <<= 1
        self._cap = cap
        self._mask = np.uint64(cap - 1)
        self._keys = np.zeros(cap, np.int64)
        self._used = np.zeros(cap, bool)
        self._slots = np.zeros(cap, np.int32)
        self._reverse = np.zeros(initial_capacity, np.int64)  # slot -> raw key

    def __del__(self):
        h = getattr(self, "_handle", None)
        if h is not None:
            try:
                self._lib.keydict_destroy(h)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass

    # -- public -------------------------------------------------------------
    @property
    def num_keys(self) -> int:
        if self._handle is not None:
            return int(self._lib.keydict_size(self._handle))
        return self._n

    def reverse_keys(self) -> np.ndarray:
        """slot id -> raw key, length num_keys."""
        if self._handle is not None:
            n = self.num_keys
            out = np.empty(n, np.int64)
            if n:
                self._lib.keydict_reverse(self._handle, out.ctypes.data)
            return out
        return self._reverse[: self._n]

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Batch lookup; returns int32 slot ids, -1 for absent keys."""
        keys = np.ascontiguousarray(keys, np.int64)
        if self._handle is not None:
            out = np.empty(keys.size, np.int32)
            if keys.size:
                self._lib.keydict_lookup(self._handle, keys.ctypes.data,
                                         keys.size, out.ctypes.data)
            return out.reshape(keys.shape)
        out = np.full(keys.shape, -1, np.int32)
        if keys.size == 0 or self._n == 0:
            return out
        idx = (_mix64(keys.view(np.uint64)) & self._mask).astype(np.int64)
        pending = np.arange(keys.size, dtype=np.int64)
        pidx = idx
        while pending.size:
            occupied = self._used[pidx]
            hit = occupied & (self._keys[pidx] == keys[pending])
            out[pending[hit]] = self._slots[pidx[hit]]
            cont = occupied & ~hit  # occupied by another key: keep probing
            pending = pending[cont]
            pidx = (pidx[cont] + 1) & np.int64(self._mask)
        return out

    def lookup_or_insert(self, keys: np.ndarray) -> np.ndarray:
        """Batch lookup, inserting unseen keys with fresh sequential slot ids."""
        keys = np.ascontiguousarray(keys, np.int64)
        if self._handle is not None:
            out = np.empty(keys.size, np.int32)
            if keys.size:
                self._lib.keydict_lookup_or_insert(
                    self._handle, keys.ctypes.data, keys.size,
                    out.ctypes.data)
            return out.reshape(keys.shape)
        if keys.size == 0:
            return np.zeros(0, np.int32)
        uniq, inv = np.unique(keys, return_inverse=True)
        uids = self._lookup_or_insert_unique(uniq)
        return uids[inv]

    # -- internals ----------------------------------------------------------
    def _lookup_or_insert_unique(self, uniq: np.ndarray) -> np.ndarray:
        if self._n + uniq.size > int(self._cap * self._max_load):
            # Only truly-new keys consume slots; a steady-state batch of
            # mostly-existing keys must not trigger doubling, so probe first.
            n_new = int(np.count_nonzero(self.lookup(uniq) < 0))
            if self._n + n_new > int(self._cap * self._max_load):
                self._grow(max(self._cap * 2, int((self._n + n_new) / self._max_load) + 1))
        out = np.full(uniq.shape, -1, np.int32)
        idx = (_mix64(uniq.view(np.uint64)) & self._mask).astype(np.int64)
        pending = np.arange(uniq.size, dtype=np.int64)
        pidx = idx
        while pending.size:
            occupied = self._used[pidx]
            hit = occupied & (self._keys[pidx] == uniq[pending])
            out[pending[hit]] = self._slots[pidx[hit]]
            # empties: race between distinct keys targeting the same bucket —
            # np.unique picks one winner per bucket, losers re-probe.
            empty = ~occupied
            e_pend = pending[empty]
            e_idx = pidx[empty]
            if e_pend.size:
                win_idx, first = np.unique(e_idx, return_index=True)
                w_pend = e_pend[first]
                new_slots = self._n + np.arange(w_pend.size, dtype=np.int32)
                self._used[win_idx] = True
                self._keys[win_idx] = uniq[w_pend]
                self._slots[win_idx] = new_slots
                self._ensure_reverse(self._n + w_pend.size)
                self._reverse[self._n: self._n + w_pend.size] = uniq[w_pend]
                self._n += int(w_pend.size)
                out[w_pend] = new_slots
            unresolved = out[pending] < 0
            pending = pending[unresolved]
            pidx = (pidx[unresolved] + 1) & np.int64(self._mask)
        return out

    def _ensure_reverse(self, n: int) -> None:
        if n > self._reverse.size:
            new = np.zeros(max(n, self._reverse.size * 2), np.int64)
            new[: self._n] = self._reverse[: self._n]
            self._reverse = new

    def _grow(self, min_cap: int) -> None:
        cap = self._cap
        while cap < min_cap:
            cap <<= 1
        old_rev = self._reverse[: self._n].copy()
        self._cap = cap
        self._mask = np.uint64(cap - 1)
        self._keys = np.zeros(cap, np.int64)
        self._used = np.zeros(cap, bool)
        self._slots = np.zeros(cap, np.int32)
        self._place_with_ids(old_rev)

    def _place_with_ids(self, keys_in_slot_order: np.ndarray) -> None:
        """Insert unique keys whose slot id == their position (vectorized);
        used by rehash-on-grow and snapshot restore."""
        n = keys_in_slot_order.size
        if not n:
            return
        idx = (_mix64(keys_in_slot_order.view(np.uint64)) & self._mask).astype(np.int64)
        pending = np.arange(n, dtype=np.int64)
        pidx = idx
        while pending.size:
            empty = ~self._used[pidx]
            e_pend = pending[empty]
            e_idx = pidx[empty]
            placed = np.zeros(pending.size, bool)
            if e_pend.size:
                win_idx, first = np.unique(e_idx, return_index=True)
                w_pend = e_pend[first]
                self._used[win_idx] = True
                self._keys[win_idx] = keys_in_slot_order[w_pend]
                self._slots[win_idx] = w_pend.astype(np.int32)
                placed_mask = np.zeros(n, bool)
                placed_mask[w_pend] = True
                placed = placed_mask[pending]
            pending = pending[~placed]
            pidx = (pidx[~placed] + 1) & np.int64(self._mask)

    # -- snapshot -----------------------------------------------------------
    def snapshot(self) -> Dict[str, np.ndarray]:
        return {"reverse": self.reverse_keys().copy()}

    @classmethod
    def restore(cls, snap: Dict[str, np.ndarray], max_load: float = 0.5) -> "KeyIndex":
        rev = np.asarray(snap["reverse"], np.int64)
        ki = cls(initial_capacity=max(1 << 16, int(rev.size / max_load) + 1), max_load=max_load)
        if ki._handle is not None:
            if rev.size:
                # inserting unique keys in slot order reproduces slot ids
                ki.lookup_or_insert(rev)
            return ki
        ki._place_with_ids(rev)
        ki._ensure_reverse(rev.size)
        ki._reverse[: rev.size] = rev
        ki._n = int(rev.size)
        return ki


class ObjectKeyIndex:
    """Object (e.g. string) key -> dense slot index.

    Batched via pandas ``factorize`` (C speed) so the Python dict is only
    touched once per *distinct new* key, amortized O(1) per record.
    """

    def __init__(self):
        self._dict: Dict[object, int] = {}
        self._reverse: List[object] = []

    @property
    def num_keys(self) -> int:
        return len(self._reverse)

    def reverse_keys(self) -> np.ndarray:
        return np.asarray(self._reverse, dtype=object)

    def lookup_or_insert(self, keys: np.ndarray) -> np.ndarray:
        import pandas as pd

        codes, uniques = pd.factorize(np.asarray(keys, dtype=object))
        if (codes < 0).any():
            # pd.factorize emits -1 for None/NaN; keys must be non-null
            # (same contract as KeyGroupRangeAssignment.java:51 checkNotNull)
            raise ValueError("null/NaN keys are not allowed in keyed streams")
        uniq_ids = np.empty(len(uniques), np.int32)
        d = self._dict
        for i, k in enumerate(uniques):
            sid = d.get(k)
            if sid is None:
                sid = len(self._reverse)
                d[k] = sid
                self._reverse.append(k)
            uniq_ids[i] = sid
        return uniq_ids[codes]

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        import pandas as pd

        codes, uniques = pd.factorize(np.asarray(keys, dtype=object))
        if len(uniques) == 0:
            return np.full(len(codes), -1, np.int32)
        uniq_ids = np.array([self._dict.get(k, -1) for k in uniques], np.int32)
        out = np.where(codes >= 0, uniq_ids[np.clip(codes, 0, None)], np.int32(-1))
        return out.astype(np.int32)

    def snapshot(self) -> Dict[str, np.ndarray]:
        return {"reverse": self.reverse_keys()}

    @classmethod
    def restore(cls, snap) -> "ObjectKeyIndex":
        ki = cls()
        for k in snap["reverse"]:
            ki._dict[k] = len(ki._reverse)
            ki._reverse.append(k)
        return ki


def make_key_index(sample_key,
                   capacity_hint: int = 0) -> "KeyIndex | ObjectKeyIndex":
    """Pick an index implementation from a sample key's dtype.

    ``capacity_hint``: expected distinct-key count — pre-sizes the table to
    2x (the load-factor bound) so a hinted run pays zero rehash-growths
    (the reference pre-sizes keyed state by maxParallelism the same way)."""
    arr = np.asarray(sample_key)
    # a composite sample (tuple of numerics) parses as an int ARRAY — it
    # must route to the object index, not the scalar int64 table
    if arr.ndim == 0 and arr.dtype.kind in "iu":
        return KeyIndex(initial_capacity=max(1 << 16, 2 * capacity_hint))
    return ObjectKeyIndex()
