"""State schema evolution: versioned snapshots + compatibility resolution.

Analog of the reference's serializer-snapshot machinery
(``TypeSerializerSnapshot.java:73`` written into every checkpoint,
``resolveSchemaCompatibility:132`` evaluated on restore, e2e-tested by
``flink-state-evolution-test``): every keyed snapshot carries a **schema
descriptor** (per-state dtype/shape/kind); on restore the old schema is
resolved against the registered descriptors:

- ``COMPATIBLE_AS_IS``      — identical layout, restore verbatim;
- ``COMPATIBLE_AFTER_MIGRATION`` — numeric widening (int32→int64,
  float32→float64, int→float) or added states: rows are cast / defaulted;
- ``INCOMPATIBLE``          — narrowing or kind changes: fail loudly
  (silent truncation is the one outcome the reference never allows).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

AS_IS = "COMPATIBLE_AS_IS"
AFTER_MIGRATION = "COMPATIBLE_AFTER_MIGRATION"
INCOMPATIBLE = "INCOMPATIBLE"

#: widening lattice: old dtype -> dtypes it may migrate to
_WIDENINGS = {
    "int8": {"int16", "int32", "int64", "float32", "float64"},
    "int16": {"int32", "int64", "float32", "float64"},
    "int32": {"int64", "float64"},
    "int64": {"float64"},
    "float32": {"float64"},
    "uint8": {"int16", "int32", "int64", "uint16", "uint32", "float32",
              "float64"},
}


def schema_of_backend(backend) -> Dict[str, Dict[str, Any]]:
    """Schema descriptor of a keyed backend's registered states."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, desc in getattr(backend, "_descs", {}).items():
        out[name] = schema_of_descriptor(desc)
    return out


def schema_of_descriptor(desc) -> Dict[str, Any]:
    dtype = getattr(desc, "dtype", None)
    return {
        "kind": getattr(desc, "kind", "value"),
        "dtype": (np.dtype(dtype).name if dtype is not None else None),
        "shape": tuple(getattr(desc, "shape", ()) or ()),
    }


def resolve_compatibility(old: Dict[str, Any],
                          new: Dict[str, Any]) -> str:
    """One state's old schema vs the newly registered descriptor
    (``resolveSchemaCompatibility`` analog)."""
    if old.get("kind") != new.get("kind"):
        return INCOMPATIBLE
    od, nd = old.get("dtype"), new.get("dtype")
    if tuple(old.get("shape", ())) != tuple(new.get("shape", ())):
        return INCOMPATIBLE
    if od == nd:
        return AS_IS
    if od is None or nd is None:
        # object-typed states (pickled rows): layout-free
        return AS_IS
    if nd in _WIDENINGS.get(od, ()):  # widening only
        return AFTER_MIGRATION
    return INCOMPATIBLE


class SchemaEvolutionError(ValueError):
    pass


def attach_schema(snapshot: Dict[str, Any], backend) -> Dict[str, Any]:
    """Write the schema descriptor into a keyed snapshot (checkpoint-time
    side of ``TypeSerializerSnapshot``)."""
    snapshot = dict(snapshot)
    snapshot["__schema__"] = schema_of_backend(backend)
    return snapshot


def migrate_snapshot(snapshot: Dict[str, Any],
                     new_descriptors: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve + migrate a keyed snapshot against the job's CURRENT state
    descriptors; returns a restorable snapshot or raises
    :class:`SchemaEvolutionError` with the exact mismatch."""
    old_schema: Dict[str, Dict[str, Any]] = snapshot.get("__schema__", {})
    out = {k: v for k, v in snapshot.items() if k != "__schema__"}
    for name, desc in new_descriptors.items():
        new_s = schema_of_descriptor(desc)
        old_s = old_schema.get(name)
        if old_s is None:
            continue  # newly ADDED state: starts empty (compatible)
        verdict = resolve_compatibility(old_s, new_s)
        if verdict == INCOMPATIBLE:
            raise SchemaEvolutionError(
                f"state {name!r}: stored schema {old_s} is incompatible with "
                f"registered descriptor {new_s} (only widening migrations "
                f"are supported)")
        if verdict == AFTER_MIGRATION:
            target = np.dtype(new_s["dtype"])
            for field in list(out):
                if field.startswith(f"state.{name}.") and \
                        isinstance(out[field], np.ndarray) and \
                        out[field].dtype != object:
                    out[field] = out[field].astype(target)
    # states present in the snapshot but no longer registered restore as-is
    # (lazy-bound, dropped when never re-registered) — reference keeps
    # unknown state until explicitly removed via the State Processor API
    return out


# ---------------------------------------------------------------------------
# composite accumulator (ACC pytree) evolution
# ---------------------------------------------------------------------------

def acc_leaf_schema(spec) -> List[Dict[str, Any]]:
    """Per-leaf schema of an accumulator pytree (written into snapshots):
    the pytree key path is the leaf's evolution identity — dict-keyed ACC
    fields migrate by NAME, the POJO field-name matching of the reference's
    ``PojoSerializerSnapshot``."""
    names = spec.leaf_names or tuple(f"[{i}]" for i in range(spec.num_leaves))
    return [{"name": n, "dtype": np.dtype(d).name}
            for n, d in zip(names, spec.leaf_dtypes)]


def migrate_acc_leaves(old_leaves, old_schema: Optional[List[Dict[str, Any]]],
                       spec, default_fill) -> List[Any]:
    """Align snapshot leaf arrays with the CURRENT accumulator spec.

    - same name, same dtype   → restored verbatim;
    - same name, widened dtype → cast (``_WIDENINGS``);
    - new leaf (field ADDED)  → ``default_fill(leaf_index)`` supplies rows
      of the identity value in the caller's row geometry;
    - old leaf gone (REMOVED) → dropped;
    - narrowing/kind change   → :class:`SchemaEvolutionError`.

    Snapshots without a recorded schema (pre-evolution) must match leaf
    count exactly.
    """
    if old_schema is None:
        if len(old_leaves) != spec.num_leaves:
            raise SchemaEvolutionError(
                f"accumulator layout changed ({len(old_leaves)} stored "
                f"leaves vs {spec.num_leaves} registered) and the snapshot "
                f"carries no leaf schema to migrate by")
        return list(old_leaves)
    new_schema = acc_leaf_schema(spec)
    by_name = {s["name"]: i for i, s in enumerate(old_schema)}
    out: List[Any] = []
    for j, ns in enumerate(new_schema):
        i = by_name.get(ns["name"])
        if i is None:
            out.append(default_fill(j))
            continue
        arr = np.asarray(old_leaves[i])
        od, nd = old_schema[i]["dtype"], ns["dtype"]
        if od != nd:
            if nd not in _WIDENINGS.get(od, ()):
                raise SchemaEvolutionError(
                    f"accumulator leaf {ns['name']!r}: stored dtype {od} -> "
                    f"registered {nd} is not a widening migration")
            arr = arr.astype(nd)
        out.append(arr)
    return out
