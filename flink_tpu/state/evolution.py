"""State schema evolution: versioned snapshots + compatibility resolution.

Analog of the reference's serializer-snapshot machinery
(``TypeSerializerSnapshot.java:73`` written into every checkpoint,
``resolveSchemaCompatibility:132`` evaluated on restore, e2e-tested by
``flink-state-evolution-test``): every keyed snapshot carries a **schema
descriptor** (per-state dtype/shape/kind); on restore the old schema is
resolved against the registered descriptors:

- ``COMPATIBLE_AS_IS``      — identical layout, restore verbatim;
- ``COMPATIBLE_AFTER_MIGRATION`` — numeric widening (int32→int64,
  float32→float64, int→float) or added states: rows are cast / defaulted;
- ``INCOMPATIBLE``          — narrowing or kind changes: fail loudly
  (silent truncation is the one outcome the reference never allows).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

AS_IS = "COMPATIBLE_AS_IS"
AFTER_MIGRATION = "COMPATIBLE_AFTER_MIGRATION"
INCOMPATIBLE = "INCOMPATIBLE"

#: widening lattice: old dtype -> dtypes it may migrate to
_WIDENINGS = {
    "int8": {"int16", "int32", "int64", "float32", "float64"},
    "int16": {"int32", "int64", "float32", "float64"},
    "int32": {"int64", "float64"},
    "int64": {"float64"},
    "float32": {"float64"},
    "uint8": {"int16", "int32", "int64", "uint16", "uint32", "float32",
              "float64"},
}


def schema_of_backend(backend) -> Dict[str, Dict[str, Any]]:
    """Schema descriptor of a keyed backend's registered states."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, desc in getattr(backend, "_descs", {}).items():
        out[name] = schema_of_descriptor(desc)
    return out


def schema_of_descriptor(desc) -> Dict[str, Any]:
    dtype = getattr(desc, "dtype", None)
    return {
        "kind": getattr(desc, "kind", "value"),
        "dtype": (np.dtype(dtype).name if dtype is not None else None),
        "shape": tuple(getattr(desc, "shape", ()) or ()),
    }


def resolve_compatibility(old: Dict[str, Any],
                          new: Dict[str, Any]) -> str:
    """One state's old schema vs the newly registered descriptor
    (``resolveSchemaCompatibility`` analog)."""
    if old.get("kind") != new.get("kind"):
        return INCOMPATIBLE
    od, nd = old.get("dtype"), new.get("dtype")
    if tuple(old.get("shape", ())) != tuple(new.get("shape", ())):
        return INCOMPATIBLE
    if od == nd:
        return AS_IS
    if od is None or nd is None:
        # object-typed states (pickled rows): layout-free
        return AS_IS
    if nd in _WIDENINGS.get(od, ()):  # widening only
        return AFTER_MIGRATION
    return INCOMPATIBLE


class SchemaEvolutionError(ValueError):
    pass


def attach_schema(snapshot: Dict[str, Any], backend) -> Dict[str, Any]:
    """Write the schema descriptor into a keyed snapshot (checkpoint-time
    side of ``TypeSerializerSnapshot``)."""
    snapshot = dict(snapshot)
    snapshot["__schema__"] = schema_of_backend(backend)
    return snapshot


def migrate_snapshot(snapshot: Dict[str, Any],
                     new_descriptors: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve + migrate a keyed snapshot against the job's CURRENT state
    descriptors; returns a restorable snapshot or raises
    :class:`SchemaEvolutionError` with the exact mismatch."""
    old_schema: Dict[str, Dict[str, Any]] = snapshot.get("__schema__", {})
    out = {k: v for k, v in snapshot.items() if k != "__schema__"}
    for name, desc in new_descriptors.items():
        new_s = schema_of_descriptor(desc)
        old_s = old_schema.get(name)
        if old_s is None:
            continue  # newly ADDED state: starts empty (compatible)
        verdict = resolve_compatibility(old_s, new_s)
        if verdict == INCOMPATIBLE:
            raise SchemaEvolutionError(
                f"state {name!r}: stored schema {old_s} is incompatible with "
                f"registered descriptor {new_s} (only widening migrations "
                f"are supported)")
        if verdict == AFTER_MIGRATION:
            target = np.dtype(new_s["dtype"])
            for field in list(out):
                if field.startswith(f"state.{name}.") and \
                        isinstance(out[field], np.ndarray) and \
                        out[field].dtype != object:
                    out[field] = out[field].astype(target)
    # states present in the snapshot but no longer registered restore as-is
    # (lazy-bound, dropped when never re-registered) — reference keeps
    # unknown state until explicitly removed via the State Processor API
    return out
