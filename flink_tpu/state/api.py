"""Keyed state API: descriptors, state handles, TTL config.

Analog of ``flink-core/src/main/java/org/apache/flink/api/common/state/``
(``StateDescriptor``, ``ValueState``/``ListState``/``MapState``/
``ReducingState``/``AggregatingState``, ``StateTtlConfig``), re-designed for a
batched TPU runtime: every state kind exposes BOTH the reference's per-key
scalar accessors (valid under a ``set_current_key``) and **vectorized
row-batch accessors** (``get_rows``/``put_rows``/``add_rows`` over dense slot
ids) — the batched path is what operators use in the hot loop, the scalar
path is the compatibility surface for host-side user code (ProcessFunction,
CEP, tests).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# TTL (StateTtlConfig analog)
# ---------------------------------------------------------------------------

class UpdateType:
    """When the TTL timestamp refreshes (StateTtlConfig.UpdateType)."""

    Disabled = "disabled"
    OnCreateAndWrite = "on_create_and_write"
    OnReadAndWrite = "on_read_and_write"


class StateVisibility:
    """Whether expired-but-not-cleaned values are returned."""

    NeverReturnExpired = "never_return_expired"
    ReturnExpiredIfNotCleanedUp = "return_expired_if_not_cleaned_up"


@dataclass(frozen=True)
class StateTtlConfig:
    """``StateTtlConfig`` analog: time-to-live for keyed state entries.

    The heap backend stores one int64 last-access timestamp per (state, slot)
    and filters expired rows vectorized on read; full-snapshot cleanup drops
    expired rows at checkpoint time (the reference's ``CleanupStrategies`` /
    full-snapshot filter, ``runtime/state/ttl/``).
    """

    ttl_ms: int
    update_type: str = UpdateType.OnCreateAndWrite
    visibility: str = StateVisibility.NeverReturnExpired
    cleanup_in_snapshot: bool = True

    def __post_init__(self):
        if self.ttl_ms <= 0:
            raise ValueError("ttl_ms must be > 0")

    @staticmethod
    def new_builder(ttl_ms: int) -> "StateTtlConfigBuilder":
        return StateTtlConfigBuilder(ttl_ms)


class StateTtlConfigBuilder:
    def __init__(self, ttl_ms: int):
        self._ttl_ms = ttl_ms
        self._update = UpdateType.OnCreateAndWrite
        self._visibility = StateVisibility.NeverReturnExpired
        self._cleanup = True

    def set_update_type(self, t: str) -> "StateTtlConfigBuilder":
        self._update = t
        return self

    def set_state_visibility(self, v: str) -> "StateTtlConfigBuilder":
        self._visibility = v
        return self

    def cleanup_full_snapshot(self, enabled: bool = True) -> "StateTtlConfigBuilder":
        self._cleanup = enabled
        return self

    def build(self) -> StateTtlConfig:
        return StateTtlConfig(self._ttl_ms, self._update, self._visibility,
                              self._cleanup)


# ---------------------------------------------------------------------------
# Descriptors (StateDescriptor analog)
# ---------------------------------------------------------------------------

class StateDescriptor:
    """Named, typed description of a piece of keyed state
    (``StateDescriptor.java`` analog). ``dtype=None`` ⇒ arbitrary Python
    objects (the Kryo-fallback analog); a numpy dtype ⇒ dense array storage
    (the fast path, device-promotable)."""

    kind: str = "value"

    def __init__(self, name: str, dtype=None, shape: Tuple[int, ...] = (),
                 default: Any = None, ttl: Optional[StateTtlConfig] = None):
        self.name = name
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.shape = tuple(shape)
        self.default = default
        self.ttl = ttl

    def enable_time_to_live(self, ttl: StateTtlConfig) -> "StateDescriptor":
        self.ttl = ttl
        return self

    def __repr__(self):
        return (f"{type(self).__name__}({self.name!r}, dtype={self.dtype}, "
                f"shape={self.shape})")


class ValueStateDescriptor(StateDescriptor):
    kind = "value"


class ListStateDescriptor(StateDescriptor):
    kind = "list"


class MapStateDescriptor(StateDescriptor):
    kind = "map"


class ReducingStateDescriptor(StateDescriptor):
    """ACC layout (dtype/shape) comes from ``reduce_fn.identity()`` — there
    are no separate dtype/shape knobs here."""

    kind = "reducing"

    def __init__(self, name: str, reduce_fn,
                 ttl: Optional[StateTtlConfig] = None):
        super().__init__(name, dtype=None, shape=(), ttl=ttl)
        self.reduce_fn = reduce_fn


class AggregatingStateDescriptor(StateDescriptor):
    kind = "aggregating"

    def __init__(self, name: str, agg, ttl: Optional[StateTtlConfig] = None):
        super().__init__(name, dtype=None, shape=(), ttl=ttl)
        self.agg = agg


# ---------------------------------------------------------------------------
# State handles (State interface analogs)
# ---------------------------------------------------------------------------

class State(abc.ABC):
    """Base handle; ``clear()`` clears the *current key*'s entry."""

    @abc.abstractmethod
    def clear(self) -> None:
        ...


class ValueState(State):
    @abc.abstractmethod
    def value(self) -> Any:
        ...

    @abc.abstractmethod
    def update(self, value: Any) -> None:
        ...

    # vectorized accessors (dense slot ids — the hot path)
    def get_rows(self, slots: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def put_rows(self, slots: np.ndarray, values: np.ndarray) -> None:
        raise NotImplementedError


class ListState(State):
    @abc.abstractmethod
    def get(self) -> List[Any]:
        ...

    @abc.abstractmethod
    def add(self, value: Any) -> None:
        ...

    def update(self, values: Iterable[Any]) -> None:
        self.clear()
        for v in values:
            self.add(v)

    def add_all(self, values: Iterable[Any]) -> None:
        for v in values:
            self.add(v)


class MapState(State):
    @abc.abstractmethod
    def get(self, key: Any) -> Any:
        ...

    @abc.abstractmethod
    def put(self, key: Any, value: Any) -> None:
        ...

    @abc.abstractmethod
    def remove(self, key: Any) -> None:
        ...

    @abc.abstractmethod
    def contains(self, key: Any) -> bool:
        ...

    @abc.abstractmethod
    def items(self) -> Iterable[Tuple[Any, Any]]:
        ...

    def keys(self):
        return (k for k, _ in self.items())

    def values(self):
        return (v for _, v in self.items())

    def is_empty(self) -> bool:
        return next(iter(self.items()), None) is None

    def put_all(self, mapping: Dict[Any, Any]) -> None:
        for k, v in mapping.items():
            self.put(k, v)


class AppendingState(State):
    """ReducingState/AggregatingState common surface (``AppendingState``)."""

    @abc.abstractmethod
    def get(self) -> Any:
        ...

    @abc.abstractmethod
    def add(self, value: Any) -> None:
        ...

    def add_rows(self, slots: np.ndarray, values) -> None:
        """Vectorized fold: merge values[i] into slot slots[i] (duplicates
        combine). This is the batched ``AggregatingState.add`` — the
        north-star per-record call, done once per micro-batch."""
        raise NotImplementedError


class ReducingState(AppendingState):
    pass


class AggregatingState(AppendingState):
    pass
