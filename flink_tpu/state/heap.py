"""Heap keyed-state backend: dense, batched, snapshot-rescalable.

Analog of ``runtime/state/heap/HeapKeyedStateBackend.java`` +
``CopyOnWriteStateMap.java`` redesigned for batched execution: instead of a
chained hash map probed per record, each state is a **dense row table**
indexed by the backend's key slot ids (``flink_tpu/state/keyindex.py``) —
numeric states are growable numpy arrays (promotable to device HBM), object
states are object arrays.  All hot-path access is vectorized
(``get_rows``/``put_rows``/``add_rows``); the scalar current-key accessors
exist for host-side user code parity with the reference API.

Snapshots are plain numpy trees in the repo-wide keyed-snapshot format
(``key_index`` + per-state row fields), so key-group splitting / merging on
rescale reuses ``flink_tpu/state/redistribute.py`` unchanged — the analog of
``StateAssignmentOperation.reDistributeKeyedStates`` (SURVEY §5.3).

Snapshot isolation (the reference's COW snapshot, ``CopyOnWriteStateMap.java:48``)
falls out of numpy value semantics: ``snapshot()`` copies row arrays, so
processing can continue while the async uploader drains the snapshot.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.state import api as state_api
from flink_tpu.state.api import (AggregatingState, AggregatingStateDescriptor,
                                 ListState, MapState, ReducingState,
                                 StateDescriptor, StateTtlConfig, UpdateType,
                                 ValueState)
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index

_ABSENT = -1


def _now_ms() -> int:
    # TTL's default clock reads through the injectable clock seam, so a
    # chaos ClockSkew schedule steers expiry deterministically
    from flink_tpu.utils.clock import now_ms
    return now_ms()


def _segment_order_spans(slots: np.ndarray):
    """Group a slot array: returns (order, [(start, end, slot), ...]) where
    ``order`` stable-sorts rows by slot and spans index the sorted view —
    the one host-side group-by used by every append-style state."""
    order = np.argsort(slots, kind="stable")
    ss = slots[order]
    bounds = np.nonzero(np.concatenate([[True], ss[1:] != ss[:-1]]))[0]
    spans = [(int(b), int(bounds[i + 1]) if i + 1 < len(bounds) else len(ss),
              int(ss[b])) for i, b in enumerate(bounds)]
    return order, spans


class _TtlTracker:
    """Per-(state,slot) last-access timestamps + vectorized expiry filter."""

    def __init__(self, ttl: StateTtlConfig, clock: Callable[[], int]):
        self.ttl = ttl
        self._clock = clock
        self._ts = np.zeros(0, np.int64)

    def _ensure(self, n: int) -> None:
        if n > self._ts.size:
            new = np.zeros(max(n, max(16, self._ts.size * 2)), np.int64)
            new[: self._ts.size] = self._ts
            self._ts = new

    def touch(self, slots: np.ndarray) -> None:
        slots = np.asarray(slots)
        if slots.size:
            self._ensure(int(slots.max()) + 1)
            self._ts[slots] = self._clock()

    def touch_on_read(self, slots: np.ndarray) -> None:
        if self.ttl.update_type == UpdateType.OnReadAndWrite:
            self.touch(slots)

    def expired(self, slots: np.ndarray) -> np.ndarray:
        """bool[B]: True where the entry is past its TTL."""
        slots = np.asarray(slots)
        self._ensure(int(slots.max()) + 1 if slots.size else 0)
        cutoff = self._clock() - self.ttl.ttl_ms
        return self._ts[slots] < cutoff

    def expired_upto(self, n: int) -> np.ndarray:
        self._ensure(n)
        cutoff = self._clock() - self.ttl.ttl_ms
        return self._ts[:n] < cutoff

    def snapshot(self, n: int) -> np.ndarray:
        self._ensure(n)
        return self._ts[:n].copy()

    def restore(self, ts: np.ndarray) -> None:
        self._ts = np.asarray(ts, np.int64).copy()


class _HeapStateBase:
    def __init__(self, backend: "HeapKeyedStateBackend", desc: StateDescriptor):
        self._backend = backend
        self._desc = desc
        self._ttl: Optional[_TtlTracker] = (
            _TtlTracker(desc.ttl, backend._clock) if desc.ttl else None)

    @property
    def name(self) -> str:
        return self._desc.name

    def _slot(self) -> int:
        s = self._backend._current_slot
        if s < 0:
            raise RuntimeError(
                f"no current key set for state {self._desc.name!r} "
                "(call backend.set_current_key first)")
        return s

    def _alive(self, slots: np.ndarray, present: np.ndarray) -> np.ndarray:
        """present mask with TTL-expired rows masked out."""
        if self._ttl is None or self._ttl.ttl.visibility != \
                state_api.StateVisibility.NeverReturnExpired:
            return present
        return present & ~self._ttl.expired(slots)

    def _touch_write(self, slots: np.ndarray) -> None:
        if self._ttl is not None:
            self._ttl.touch(slots)

    def _purge_expired_before_append(self, slots: np.ndarray) -> None:
        """Appending into an expired entry must not resurrect the old
        content: clear expired slots before folding new values in (the
        reference's TTL decorators never merge into expired state)."""
        if self._ttl is None:
            return
        slots = np.unique(np.asarray(slots, np.int64))
        dead = slots[self._ttl.expired(slots)]
        if dead.size:
            self.clear_rows(dead)

    def _touch_read(self, slots: np.ndarray) -> None:
        if self._ttl is not None:
            self._ttl.touch_on_read(slots)

    # snapshot plumbing — subclasses fill "rows"
    def _snapshot_common(self, n: int, snap: Dict[str, Any]) -> Dict[str, Any]:
        if self._ttl is not None:
            snap["ttl_ts"] = self._ttl.snapshot(n)
            if self._ttl.ttl.cleanup_in_snapshot:
                # full-snapshot cleanup: drop expired rows from the snapshot
                snap["ttl_expired"] = self._ttl.expired_upto(n).copy()
        return snap


class _DenseGrow:
    """Growable dense [cap, *shape] array + present mask."""

    def __init__(self, dtype: np.dtype, shape: Tuple[int, ...], default):
        self.dtype, self.shape = dtype, shape
        self.default = default
        self.data = np.zeros((0,) + shape, dtype)
        self.present = np.zeros(0, bool)

    def ensure(self, n: int) -> None:
        if n > self.data.shape[0]:
            cap = max(n, max(16, self.data.shape[0] * 2))
            nd = np.zeros((cap,) + self.shape, self.dtype)
            nd[: self.data.shape[0]] = self.data
            np_p = np.zeros(cap, bool)
            np_p[: self.present.size] = self.present
            self.data, self.present = nd, np_p

    def default_rows(self, n: int) -> np.ndarray:
        out = np.zeros((n,) + self.shape, self.dtype)
        if self.default is not None:
            out[:] = self.default
        return out


class HeapValueState(ValueState, _HeapStateBase):
    """Dense numeric ValueState (numpy row table) or object ValueState."""

    def __init__(self, backend, desc: StateDescriptor):
        _HeapStateBase.__init__(self, backend, desc)
        self._dense = (_DenseGrow(desc.dtype, desc.shape, desc.default)
                       if desc.dtype is not None else None)
        self._objs: List[Any] = []
        self._obj_present = np.zeros(0, bool)

    # -- vectorized ---------------------------------------------------------
    def get_rows(self, slots: np.ndarray):
        slots = np.asarray(slots, np.int64)
        if self._dense is not None:
            self._dense.ensure(int(slots.max()) + 1 if slots.size else 0)
            alive = self._alive(slots, self._dense.present[slots])
            out = self._dense.data[slots].copy()
            if self._desc.default is not None:
                out[~alive] = self._desc.default
            else:
                out[~alive] = 0
            self._touch_read(slots)
            return out, alive
        vals = [self._objs[s] if (s < len(self._objs)) else None for s in slots]
        present = np.array([s < self._obj_present.size and self._obj_present[s]
                            for s in slots], bool)
        alive = self._alive(slots, present)
        self._touch_read(slots)
        return np.array([v if a else self._desc.default
                         for v, a in zip(vals, alive)], object), alive

    def put_rows(self, slots: np.ndarray, values) -> None:
        slots = np.asarray(slots, np.int64)
        if not slots.size:
            return
        n = int(slots.max()) + 1
        if self._dense is not None:
            self._dense.ensure(n)
            self._dense.data[slots] = np.asarray(values, self._dense.dtype)
            self._dense.present[slots] = True
        else:
            self._ensure_objs(n)
            for s, v in zip(slots, values):
                self._objs[s] = v
            self._obj_present[slots] = True
        self._touch_write(slots)

    def clear_rows(self, slots: np.ndarray) -> None:
        slots = np.asarray(slots, np.int64)
        if not slots.size:
            return
        if self._dense is not None:
            self._dense.ensure(int(slots.max()) + 1)
            self._dense.present[slots] = False
        else:
            self._ensure_objs(int(slots.max()) + 1)
            self._obj_present[slots] = False
            for s in slots:
                self._objs[s] = None

    def _ensure_objs(self, n: int) -> None:
        while len(self._objs) < n:
            self._objs.append(None)
        if n > self._obj_present.size:
            p = np.zeros(max(n, max(16, self._obj_present.size * 2)), bool)
            p[: self._obj_present.size] = self._obj_present
            self._obj_present = p

    # -- scalar (current key) ----------------------------------------------
    def value(self):
        vals, alive = self.get_rows(np.array([self._slot()]))
        return (vals[0] if alive[0] else self._desc.default)

    def update(self, value) -> None:
        self.put_rows(np.array([self._slot()]), [value])

    def clear(self) -> None:
        self.clear_rows(np.array([self._slot()]))

    # -- snapshot ------------------------------------------------------------
    def snapshot(self, n: int) -> Dict[str, Any]:
        if self._dense is not None:
            self._dense.ensure(n)
            snap = {"rows": self._dense.data[:n].copy(),
                    "present": self._dense.present[:n].copy()}
        else:
            self._ensure_objs(n)
            rows = np.empty(n, object)
            rows[:] = self._objs[:n]
            snap = {"rows": rows, "present": self._obj_present[:n].copy()}
        return self._snapshot_common(n, snap)

    def restore(self, snap: Dict[str, Any]) -> None:
        rows, present = snap["rows"], np.asarray(snap["present"], bool)
        if "ttl_expired" in snap:
            present = present & ~np.asarray(snap["ttl_expired"], bool)
        n = len(present)
        if self._dense is not None:
            self._dense.ensure(n)
            self._dense.data[:n] = rows
            self._dense.present[:n] = present
        else:
            self._ensure_objs(n)
            for i in range(n):
                self._objs[i] = rows[i]
            self._obj_present[:n] = present
        if self._ttl is not None and "ttl_ts" in snap:
            self._ttl.restore(snap["ttl_ts"])


class HeapListState(ListState, _HeapStateBase):
    """Per-slot Python list (object path).  ``add_rows`` appends a whole batch
    grouped by slot in one argsort pass (no per-record dict probing)."""

    def __init__(self, backend, desc: StateDescriptor):
        _HeapStateBase.__init__(self, backend, desc)
        self._lists: List[Optional[list]] = []

    def _ensure(self, n: int) -> None:
        while len(self._lists) < n:
            self._lists.append(None)

    def add_rows(self, slots: np.ndarray, values) -> None:
        slots = np.asarray(slots, np.int64)
        if not slots.size:
            return
        self._ensure(int(slots.max()) + 1)
        self._purge_expired_before_append(slots)
        order, spans = _segment_order_spans(slots)
        vals = np.asarray(values, object)[order]
        for b, e, s in spans:
            if self._lists[s] is None:
                self._lists[s] = []
            self._lists[s].extend(vals[b:e].tolist())
        self._touch_write(np.unique(slots))

    def get_rows(self, slots: np.ndarray) -> List[list]:
        slots = np.asarray(slots, np.int64)
        self._ensure(int(slots.max()) + 1 if slots.size else 0)
        present = np.array([self._lists[s] is not None for s in slots], bool)
        alive = self._alive(slots, present)
        self._touch_read(slots)
        return [list(self._lists[s]) if a else []
                for s, a in zip(slots, alive)]

    def get(self) -> list:
        return self.get_rows(np.array([self._slot()]))[0]

    def add(self, value) -> None:
        self.add_rows(np.array([self._slot()]), [value])

    def update(self, values) -> None:
        s = self._slot()
        self._ensure(s + 1)
        self._lists[s] = list(values)
        self._touch_write(np.array([s]))

    def clear(self) -> None:
        s = self._slot()
        self._ensure(s + 1)
        self._lists[s] = None

    def clear_rows(self, slots: np.ndarray) -> None:
        slots = np.asarray(slots, np.int64)
        if slots.size:
            self._ensure(int(slots.max()) + 1)
            for s in slots:
                self._lists[s] = None

    def snapshot(self, n: int) -> Dict[str, Any]:
        self._ensure(n)
        rows = np.empty(n, object)
        rows[:] = [None if l is None else list(l) for l in self._lists[:n]]
        return self._snapshot_common(n, {"rows": rows})

    def restore(self, snap: Dict[str, Any]) -> None:
        rows = snap["rows"]
        expired = snap.get("ttl_expired")
        self._lists = [
            None if (r is None or (expired is not None and expired[i]))
            else list(r)
            for i, r in enumerate(rows)]
        if self._ttl is not None and "ttl_ts" in snap:
            self._ttl.restore(snap["ttl_ts"])


class HeapMapState(MapState, _HeapStateBase):
    def __init__(self, backend, desc: StateDescriptor):
        _HeapStateBase.__init__(self, backend, desc)
        self._maps: List[Optional[dict]] = []

    def _ensure(self, n: int) -> None:
        while len(self._maps) < n:
            self._maps.append(None)

    def clear_rows(self, slots: np.ndarray) -> None:
        slots = np.asarray(slots, np.int64)
        if slots.size:
            self._ensure(int(slots.max()) + 1)
            for s in slots:
                self._maps[s] = None

    def _map(self, create: bool = False) -> Optional[dict]:
        s = self._slot()
        self._ensure(s + 1)
        if create and self._maps[s] is not None and self._ttl is not None \
                and self._ttl.expired(np.array([s]))[0]:
            self._maps[s] = None  # writing into an expired map starts fresh
        if self._maps[s] is None and create:
            self._maps[s] = {}
        if self._maps[s] is not None:
            arr = np.array([s])
            if create:
                self._touch_write(arr)
            else:
                alive = self._alive(arr, np.array([True]))
                if not alive[0]:
                    self._maps[s] = None
                    return None
                self._touch_read(arr)
        return self._maps[s]

    def get(self, key):
        m = self._map()
        return None if m is None else m.get(key)

    def put(self, key, value) -> None:
        self._map(create=True)[key] = value

    def remove(self, key) -> None:
        m = self._map()
        if m is not None:
            m.pop(key, None)

    def contains(self, key) -> bool:
        m = self._map()
        return m is not None and key in m

    def items(self):
        m = self._map()
        return [] if m is None else list(m.items())

    def clear(self) -> None:
        s = self._slot()
        self._ensure(s + 1)
        self._maps[s] = None

    def maps_rows(self, slots: np.ndarray) -> List[Optional[dict]]:
        slots = np.asarray(slots, np.int64)
        self._ensure(int(slots.max()) + 1 if slots.size else 0)
        return [self._maps[s] for s in slots]

    def snapshot(self, n: int) -> Dict[str, Any]:
        self._ensure(n)
        rows = np.empty(n, object)
        rows[:] = [None if m is None else dict(m) for m in self._maps[:n]]
        return self._snapshot_common(n, {"rows": rows})

    def restore(self, snap: Dict[str, Any]) -> None:
        rows = snap["rows"]
        expired = snap.get("ttl_expired")
        self._maps = [
            None if (r is None or (expired is not None and expired[i]))
            else dict(r)
            for i, r in enumerate(rows)]
        if self._ttl is not None and "ttl_ts" in snap:
            self._ttl.restore(snap["ttl_ts"])


class HeapAggregatingState(AggregatingState, _HeapStateBase):
    """Dense ACC rows per slot; the batched analog of
    ``HeapAggregatingState.java:42``.  ``add_rows`` folds a whole batch with
    numpy ufunc scatters (add/min/max fast path) or a sort+reduce fold for
    arbitrary monoids — mirroring the device kernels in
    ``flink_tpu/ops/scatter.py`` on the host tier."""

    def __init__(self, backend, desc: AggregatingStateDescriptor):
        _HeapStateBase.__init__(self, backend, desc)
        self.agg = desc.agg
        spec = self.agg.acc_spec()
        self._spec = spec
        self._leaves = [np.zeros((0,) + s, d)
                        for s, d in zip(spec.leaf_shapes, spec.leaf_dtypes)]
        self._present = np.zeros(0, bool)
        self._kinds = self.agg.scatter_kind_leaves()

    def _ensure(self, n: int) -> None:
        if n > self._present.size:
            cap = max(n, max(16, self._present.size * 2))
            new_leaves = []
            for leaf, init in zip(self._leaves, self._spec.leaf_inits):
                nd = np.empty((cap,) + leaf.shape[1:], leaf.dtype)
                nd[:] = init
                nd[: leaf.shape[0]] = leaf
                new_leaves.append(nd)
            self._leaves = new_leaves
            p = np.zeros(cap, bool)
            p[: self._present.size] = self._present
            self._present = p

    def add_rows(self, slots: np.ndarray, values) -> None:
        import jax

        slots = np.asarray(slots, np.int64)
        if not slots.size:
            return
        self._ensure(int(slots.max()) + 1)
        self._purge_expired_before_append(slots)
        lifted = jax.tree_util.tree_leaves(self.agg.lift(values))
        lifted = [np.asarray(l) for l in lifted]
        if self._kinds is not None:
            from flink_tpu.core.functions import SCATTER_UFUNCS
            for leaf, l, kind in zip(self._leaves, lifted, self._kinds):
                SCATTER_UFUNCS[kind].at(leaf, slots, l.astype(leaf.dtype))
        else:
            order, spans = _segment_order_spans(slots)
            sv = [l[order] for l in lifted]
            for b, e, s in spans:
                acc = tuple(leaf[s] for leaf in self._leaves)
                for j in range(b, e):
                    acc = tuple(np.asarray(x) for x in self.agg.combine_leaves(
                        acc, tuple(l[j] for l in sv)))
                for leaf, a in zip(self._leaves, acc):
                    leaf[s] = a
        self._present[slots] = True
        self._touch_write(np.unique(slots))

    def get_rows(self, slots: np.ndarray):
        """Returns (results, alive): vectorized get_result over slots.
        Results are an array for scalar-valued aggregates, or a dict of
        arrays for composite results (e.g. TupleAggregator)."""
        slots = np.asarray(slots, np.int64)
        self._ensure(int(slots.max()) + 1 if slots.size else 0)
        alive = self._alive(slots, self._present[slots])
        acc = self._spec.unflatten([leaf[slots] for leaf in self._leaves])
        self._touch_read(slots)
        res = self.agg.get_result(acc)
        if isinstance(res, dict):
            return {k: np.asarray(v) for k, v in res.items()}, alive
        return np.asarray(res), alive

    def get(self):
        res, alive = self.get_rows(np.array([self._slot()]))
        if isinstance(res, dict):
            # composite result (dict-ACC aggregates): one row -> one dict
            return ({k: v[0].item() if hasattr(v[0], "item") else v[0]
                     for k, v in res.items()} if alive[0] else None)
        return res[0] if alive[0] else None

    def add(self, value) -> None:
        self.add_rows(np.array([self._slot()]), np.asarray([value]))

    def clear(self) -> None:
        self.clear_rows(np.array([self._slot()]))

    def clear_rows(self, slots: np.ndarray) -> None:
        slots = np.asarray(slots, np.int64)
        if not slots.size:
            return
        self._ensure(int(slots.max()) + 1)
        for leaf, init in zip(self._leaves, self._spec.leaf_inits):
            leaf[slots] = init
        self._present[slots] = False

    def snapshot(self, n: int) -> Dict[str, Any]:
        from flink_tpu.state.evolution import acc_leaf_schema

        self._ensure(n)
        return self._snapshot_common(n, {
            "rows": tuple(leaf[:n].copy() for leaf in self._leaves),
            "leaf_schema": acc_leaf_schema(self._spec),
            "present": self._present[:n].copy()})

    def restore(self, snap: Dict[str, Any]) -> None:
        from flink_tpu.state.evolution import migrate_acc_leaves

        present = np.asarray(snap["present"], bool)
        if "ttl_expired" in snap:
            present = present & ~np.asarray(snap["ttl_expired"], bool)
        n = len(present)
        self._ensure(n)

        def fill(j, _n=n):
            init = np.asarray(self._spec.leaf_inits[j],
                              self._spec.leaf_dtypes[j])
            return np.broadcast_to(
                init, (_n,) + tuple(self._spec.leaf_shapes[j])).copy()

        rows = migrate_acc_leaves(snap["rows"], snap.get("leaf_schema"),
                                  self._spec, fill)
        for leaf, r in zip(self._leaves, rows):
            leaf[:n] = r
        self._present[:n] = present
        if self._ttl is not None and "ttl_ts" in snap:
            self._ttl.restore(snap["ttl_ts"])


class HeapReducingState(HeapAggregatingState, ReducingState):
    """ReducingState == AggregatingState whose ACC is the value type
    (``HeapReducingState.java`` analog)."""

    def __init__(self, backend, desc):
        agg_desc = AggregatingStateDescriptor(desc.name, desc.reduce_fn,
                                              ttl=desc.ttl)
        super().__init__(backend, agg_desc)


#: every field a state impl may put in its snapshot dict (restore parses
#: flattened "state.<name>.<field>" keys against this closed set)
_STATE_SNAPSHOT_FIELDS = ("rows", "present", "ttl_ts", "ttl_expired",
                          "leaf_schema")

_IMPLS = {
    "value": HeapValueState,
    "list": HeapListState,
    "map": HeapMapState,
    "reducing": HeapReducingState,
    "aggregating": HeapAggregatingState,
}


class HeapKeyedStateBackend:
    """Keyed state backend: owns the key→slot index and all named states.

    One backend per keyed operator subtask (as in the reference, one
    ``HeapKeyedStateBackend`` per ``AbstractStreamOperator``); the key slots
    it hands out double as row ids into every registered state table AND into
    the operator's device arrays — a single key universe per operator.
    """

    def __init__(self, max_parallelism: int = 128,
                 clock: Callable[[], int] = _now_ms):
        self.max_parallelism = max_parallelism
        self._clock = clock
        self._index: Optional[KeyIndex | ObjectKeyIndex] = None
        self._states: Dict[str, _HeapStateBase] = {}
        self._descs: Dict[str, StateDescriptor] = {}
        self._pending_restore: Dict[str, Dict[str, Any]] = {}
        self._current_slot = _ABSENT

    # -- keys ----------------------------------------------------------------
    @property
    def num_keys(self) -> int:
        return 0 if self._index is None else self._index.num_keys

    def _ensure_index(self, sample_key):
        if self._index is None:
            self._index = make_key_index(sample_key)
        return self._index

    def key_slots(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized key -> dense slot (inserting new keys)."""
        keys = np.asarray(keys)
        if keys.size == 0:
            return np.zeros(0, np.int32)
        return self._ensure_index(keys[0]).lookup_or_insert(keys)

    def set_current_key(self, key) -> None:
        self._current_slot = int(self.key_slots(np.asarray([key]))[0])

    def current_slot(self) -> int:
        return self._current_slot

    def slot_keys(self, slots: np.ndarray) -> np.ndarray:
        """slot ids -> raw keys (for emitting results)."""
        rev = self._index.reverse_keys()
        return np.asarray(rev)[np.asarray(slots)]

    # -- states --------------------------------------------------------------
    def get_state(self, desc: StateDescriptor):
        """``getPartitionedState`` analog: create-or-return the named state."""
        st = self._states.get(desc.name)
        if st is None:
            st = _IMPLS[desc.kind](self, desc)
            self._states[desc.name] = st
            self._descs[desc.name] = desc
            pending = self._pending_restore.pop(desc.name, None)
            if pending is not None:
                # restored snapshot binds when the descriptor registers —
                # same contract as the reference's getPartitionedState.
                # Schema compatibility resolves HERE (the reference's
                # resolveSchemaCompatibility on first state access).
                pending = self._resolve_schema(desc, pending)
                st.restore(pending)
        return st

    def _resolve_schema(self, desc: StateDescriptor,
                        pending: Dict[str, Any]) -> Dict[str, Any]:
        from flink_tpu.state.evolution import (AFTER_MIGRATION, INCOMPATIBLE,
                                               SchemaEvolutionError,
                                               resolve_compatibility,
                                               schema_of_descriptor)
        old = getattr(self, "_restored_schema", {}).get(desc.name)
        if old is None:
            return pending
        new = schema_of_descriptor(desc)
        verdict = resolve_compatibility(old, new)
        if verdict == INCOMPATIBLE:
            raise SchemaEvolutionError(
                f"state {desc.name!r}: stored schema {old} cannot restore "
                f"into descriptor schema {new} (only widening migrations "
                f"are supported)")
        if verdict == AFTER_MIGRATION:
            import numpy as _np
            target = _np.dtype(new["dtype"])
            # ONLY the value rows migrate — bookkeeping fields (ttl_ts
            # timestamps, presence flags) keep their own dtypes
            pending = {
                f: (v.astype(target)
                    if f == "rows" and isinstance(v, _np.ndarray)
                    and v.dtype != object
                    and _np.issubdtype(v.dtype, _np.number) else v)
                for f, v in pending.items()}
        return pending

    def value_state(self, name: str, **kw) -> HeapValueState:
        return self.get_state(state_api.ValueStateDescriptor(name, **kw))

    def list_state(self, name: str, **kw) -> HeapListState:
        return self.get_state(state_api.ListStateDescriptor(name, **kw))

    def map_state(self, name: str, **kw) -> HeapMapState:
        return self.get_state(state_api.MapStateDescriptor(name, **kw))

    def reducing_state(self, name: str, reduce_fn, **kw) -> HeapReducingState:
        return self.get_state(
            state_api.ReducingStateDescriptor(name, reduce_fn, **kw))

    def aggregating_state(self, name: str, agg, **kw) -> HeapAggregatingState:
        return self.get_state(
            state_api.AggregatingStateDescriptor(name, agg, **kw))

    # -- snapshot / restore --------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Repo-standard keyed snapshot: ``key_index`` + one row field per
        state, splittable by ``redistribute.split_keyed_snapshot``."""
        if self._index is None:
            return {"empty": True}
        n = self.num_keys
        snap: Dict[str, Any] = {
            "key_index": self._index.snapshot(),
            "key_index_kind": type(self._index).__name__,
            "num_keys": n,
            "state_names": sorted(set(self._states) | set(self._pending_restore)),
        }
        for name, st in self._states.items():
            sub = st.snapshot(n)
            for f, v in sub.items():
                snap[f"state.{name}.{f}"] = v
        # restored states whose descriptor hasn't re-registered yet must be
        # carried through verbatim, or a restore→checkpoint cycle loses them
        for name, sub in self._pending_restore.items():
            for f, v in sub.items():
                snap[f"state.{name}.{f}"] = v
        # serializer-snapshot analog: per-state schema rides the checkpoint
        from flink_tpu.state.evolution import schema_of_backend
        schema = schema_of_backend(self)
        # states restored-but-not-rebound keep their stored schema
        for name, s in getattr(self, "_restored_schema", {}).items():
            schema.setdefault(name, s)
        snap["__schema__"] = schema
        return snap

    @staticmethod
    def row_fields(snap: Dict[str, Any]) -> List[str]:
        """The per-key row fields of a backend snapshot (for redistribute).
        ``leaf_schema`` entries are per-STATE metadata, not per-key rows —
        splitting them by key group would corrupt them."""
        return [k for k in snap if k.startswith("state.")
                and not k.endswith(".leaf_schema")]

    def restore(self, snap: Dict[str, Any]) -> None:
        if snap.get("empty"):
            return
        self._restored_schema = dict(snap.get("__schema__", {}))
        kind = snap.get("key_index_kind", "KeyIndex")
        cls = ObjectKeyIndex if kind == "ObjectKeyIndex" else KeyIndex
        self._index = cls.restore(snap["key_index"])
        for name in snap.get("state_names", []):
            # match against the KNOWN field suffixes so a state name
            # containing '.' (or one that prefixes another) parses correctly
            sub = {}
            for f in _STATE_SNAPSHOT_FIELDS:
                key = f"state.{name}.{f}"
                if key in snap:
                    sub[f] = snap[key]
            st = self._states.get(name)
            if st is None:
                self._pending_restore[name] = sub  # lazy-bind on registration
            else:
                st.restore(sub)
