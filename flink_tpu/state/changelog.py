"""Changelog keyed-state backend: log mutations, materialize periodically.

Analog of the reference's changelog state backend
(``flink-statebackend-changelog/.../ChangelogKeyedStateBackend.java``,
``ChangelogAggregatingState.java``): wraps ANY inner keyed backend and
records every state mutation into an in-order changelog.  A checkpoint is
then ``(last materialized snapshot, changelog suffix)`` — near-constant-size
when mutations since the last materialization are few, enabling very frequent
checkpoints; ``materialize()`` takes a full inner snapshot and truncates the
log (the periodic materialization of the reference).

Replay correctness: key-slot assignment is part of the log — ``key_slots`` /
``set_current_key`` calls are recorded, so replay reproduces identical dense
slot ids in the restored inner backend.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.state.api import StateDescriptor

#: mutating methods per state flavor — everything else passes through as read
_MUTATORS = {
    "update", "clear", "add", "add_all", "add_rows", "put", "put_all",
    "put_rows", "remove", "clear_rows",
}


class _ChangelogStateProxy:
    """Forwards reads to the inner state; records + forwards mutations."""

    def __init__(self, backend: "ChangelogKeyedStateBackend", name: str,
                 inner_state):
        object.__setattr__(self, "_backend", backend)
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_inner", inner_state)

    def __getattr__(self, attr: str):
        target = getattr(self._inner, attr)
        if attr in _MUTATORS and callable(target):
            name = self._name
            backend = self._backend

            def recorded(*args, **kwargs):
                backend._log.append(("mutate", name, attr, args, kwargs))
                return target(*args, **kwargs)

            return recorded
        return target


class ChangelogKeyedStateBackend:
    """Wraps an inner keyed backend with a state changelog."""

    def __init__(self, inner):
        self.inner = inner
        self._log: List[Tuple] = []
        self._materialized: Optional[Dict[str, Any]] = None
        self._states: Dict[str, _ChangelogStateProxy] = {}
        self._descs: Dict[str, StateDescriptor] = {}
        # ---- incremental checkpointing (ISSUE-16): a cut may ship only the
        # log SUFFIX beyond the last CONFIRMED checkpoint's log position,
        # valid only within one materialization epoch (materialize() re-bases
        # the log, so positions across epochs are incomparable)
        self._epoch = 0
        #: auto-materialize when the log outgrows this (0 = manual only)
        self.materialize_threshold = 0
        self._unconfirmed: List[Tuple[int, int, int]] = []  # (cid,epoch,len)
        self._confirmed: Optional[Tuple[int, int]] = None   # (epoch, len)

    def reserve_managed(self, manager, owner: str) -> None:
        """Forward the managed-memory claim to the wrapped backend (the
        changelog itself is unbudgeted bookkeeping; the spill tier inside
        is what holds resident bytes)."""
        if hasattr(self.inner, "reserve_managed"):
            self.inner.reserve_managed(manager, owner)

    def close(self) -> None:
        if hasattr(self.inner, "close"):
            self.inner.close()

    # -- key plumbing (recorded: slot assignment must replay identically) ----
    @property
    def max_parallelism(self) -> int:
        return self.inner.max_parallelism

    @max_parallelism.setter
    def max_parallelism(self, value: int) -> None:
        self.inner.max_parallelism = value

    @property
    def num_keys(self) -> int:
        return self.inner.num_keys

    def key_slots(self, keys: np.ndarray) -> np.ndarray:
        self._log.append(("key_slots", np.asarray(keys)))
        return self.inner.key_slots(keys)

    def set_current_key(self, key) -> None:
        self._log.append(("set_current_key", key))
        self.inner.set_current_key(key)

    def current_slot(self) -> int:
        return self.inner.current_slot()

    def slot_keys(self, slots: np.ndarray) -> np.ndarray:
        return self.inner.slot_keys(slots)

    # -- states --------------------------------------------------------------
    def get_state(self, desc: StateDescriptor):
        proxy = self._states.get(desc.name)
        if proxy is None:
            self._log.append(("register", desc))
            proxy = _ChangelogStateProxy(self, desc.name,
                                         self.inner.get_state(desc))
            self._states[desc.name] = proxy
            self._descs[desc.name] = desc
        return proxy

    def value_state(self, name: str, **kw):
        from flink_tpu.state import api as state_api
        return self.get_state(state_api.ValueStateDescriptor(name, **kw))

    def list_state(self, name: str, **kw):
        from flink_tpu.state import api as state_api
        return self.get_state(state_api.ListStateDescriptor(name, **kw))

    def map_state(self, name: str, **kw):
        from flink_tpu.state import api as state_api
        return self.get_state(state_api.MapStateDescriptor(name, **kw))

    def reducing_state(self, name: str, reduce_fn, **kw):
        from flink_tpu.state import api as state_api
        return self.get_state(
            state_api.ReducingStateDescriptor(name, reduce_fn, **kw))

    def aggregating_state(self, name: str, agg, **kw):
        from flink_tpu.state import api as state_api
        return self.get_state(state_api.AggregatingStateDescriptor(name, agg, **kw))

    # -- changelog lifecycle -------------------------------------------------
    def materialize(self) -> None:
        """Full inner snapshot; truncate the log (periodic materialization).
        The truncated log is re-seeded with register entries so later
        mutations of already-known states stay replayable."""
        from flink_tpu.testing import chaos
        chaos.fire("checkpoint.materialize", log_size=len(self._log))
        self._materialized = self.inner.snapshot()
        self._log = [("register", d) for d in self._descs.values()]
        self._epoch += 1    # log positions of older epochs are now invalid

    def changelog_size(self) -> int:
        return len(self._log)

    def snapshot(self) -> Dict[str, Any]:
        """(materialized base, changelog suffix) — cheap when the log is
        short; callers trigger ``materialize()`` on their own cadence."""
        return {
            "changelog_backend": True,
            "materialized": self._materialized,
            "changelog": list(self._log),
        }

    def snapshot_increment(self, checkpoint_id: int):
        """A ``changelog`` increment node (runtime/checkpoint/delta.py) with
        the log suffix beyond the last CONFIRMED cut, or None when this cut
        must ship the full snapshot (no confirmed base, or a materialization
        re-based the log since).  Freezes the cut position either way, so
        later cuts keep covering it until ``notify_checkpoint_complete``."""
        if self.materialize_threshold \
                and len(self._log) >= self.materialize_threshold:
            self.materialize()   # background re-base: this cut goes full
        self._unconfirmed.append((checkpoint_id, self._epoch,
                                  len(self._log)))
        if self._confirmed is None or self._confirmed[0] != self._epoch:
            return None
        log_base = self._confirmed[1]
        return {
            "__increment__": 1, "kind": "changelog",
            "checkpoint_id": checkpoint_id,
            "log_base": log_base,
            "log_suffix": list(self._log[log_base:]),
            "extras": {},
        }

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Advance the confirmed log position to a cut this backend froze
        (savepoints/finals never call ``snapshot_increment`` and therefore
        never advance the increment chain)."""
        match = next((e for e in self._unconfirmed
                      if e[0] == checkpoint_id), None)
        if match is not None:
            self._unconfirmed = [e for e in self._unconfirmed
                                 if e[0] > checkpoint_id]
            self._confirmed = (match[1], match[2])

    def restore(self, snap: Dict[str, Any]) -> None:
        # restored state severs the linkage to any storage-side increment
        # chain: the first cut after restore is a full base
        self._unconfirmed = []
        self._confirmed = None
        self._epoch += 1
        if not snap.get("changelog_backend"):
            # plain inner snapshot (e.g. pre-changelog checkpoint)
            self.inner.restore(snap)
            return
        if snap.get("materialized") is not None:
            self.inner.restore(snap["materialized"])
        self._materialized = snap.get("materialized")
        self._states = {}
        replayed: Dict[str, Any] = {}
        for entry in snap.get("changelog", []):
            kind = entry[0]
            if kind == "key_slots":
                self.inner.key_slots(entry[1])
            elif kind == "set_current_key":
                self.inner.set_current_key(entry[1])
            elif kind == "register":
                desc = entry[1]
                replayed[desc.name] = self.inner.get_state(desc)
                self._states[desc.name] = _ChangelogStateProxy(
                    self, desc.name, replayed[desc.name])
                self._descs[desc.name] = desc
            elif kind == "mutate":
                _, name, attr, args, kwargs = entry
                getattr(replayed[name], attr)(*args, **kwargs)
        # the restored log IS the current log: a snapshot taken now must
        # still contain these mutations relative to the same base
        self._log = list(snap.get("changelog", []))
