"""Spill-tier keyed state backend over the native C++ SpillStore.

The RocksDB-backend analog (SURVEY §2.6: ``RocksDBKeyedStateBackend.java``,
column-family-per-state, managed-memory block cache): keyed state lives as
serialized per-(state, key-slot) entries in a memory-budgeted native KV store
(:class:`flink_tpu.native.SpillStore`) that evicts cold values to an
append-only disk log — state larger than host RAM keeps working, the general
capability claim of SURVEY §7.3 "State larger than HBM".

Same public surface as :class:`flink_tpu.state.heap.HeapKeyedStateBackend`
(key slots, ``get_state``, snapshot/restore in the repo-standard keyed
snapshot format) so operators and ``redistribute.split_keyed_snapshot``
work unchanged; selected via ``state.backend: spill`` (``StateBackendOptions``
analog).  The hot windowed path stays on the heap/HBM backend — this is the
cold/large tier, per-entry access cost is one native hash probe + pickle.
"""

from __future__ import annotations

import os
import pickle
import struct
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.native import SpillStore
from flink_tpu.state import api as state_api
from flink_tpu.state.api import (AggregatingState, AggregatingStateDescriptor,
                                 ListState, MapState, ReducingState,
                                 ReducingStateDescriptor, StateDescriptor,
                                 ValueState)
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index

_ABSENT = -1


def _now_ms() -> int:
    # TTL default clock: the injectable seam (chaos ClockSkew-aware)
    from flink_tpu.utils.clock import now_ms
    return now_ms()


class _SpillStateBase:
    kind = "value"

    def __init__(self, backend: "SpillKeyedStateBackend", desc: StateDescriptor):
        self.backend = backend
        self.desc = desc
        self._prefix = desc.name.encode() + b"\x00"

    @property
    def name(self) -> str:
        return self.desc.name

    def _key(self, slot: int) -> bytes:
        return self._prefix + struct.pack("<I", slot)

    def _load(self, slot: int):
        raw = self.backend.store.get(self._key(slot))
        if raw is None:
            return None
        ts, value = pickle.loads(raw)
        ttl = self.desc.ttl
        if ttl is not None and self.backend._clock() - ts >= ttl.ttl_ms:
            return None
        return value

    def _save(self, slot: int, value) -> None:
        self.backend.store.put(self._key(slot),
                               pickle.dumps((self.backend._clock(), value)))

    def _drop(self, slot: int) -> None:
        self.backend.store.delete(self._key(slot))

    def _slot(self) -> int:
        s = self.backend.current_slot()
        if s == _ABSENT:
            raise RuntimeError("no current key set on spill backend")
        return s

    def clear(self) -> None:
        self._drop(self._slot())

    def clear_rows(self, slots: np.ndarray) -> None:
        for s in np.asarray(slots).tolist():
            self._drop(int(s))

    # snapshot plumbing: one object-array row field of raw blobs (restore is
    # kind-agnostic — blobs land back in the store under the same keys)
    def snapshot(self, n: int) -> Dict[str, Any]:
        rows = np.empty(n, dtype=object)
        for slot in range(n):
            rows[slot] = self.backend.store.get(self._key(slot))
        return {"rows": rows}

    def restore(self, snap: Dict[str, Any]) -> None:
        rows = snap["rows"]
        for slot, blob in enumerate(rows):
            if blob is not None:
                self.backend.store.put(self._key(int(slot)), bytes(blob))


class SpillValueState(_SpillStateBase, ValueState):
    kind = "value"

    def value(self):
        v = self._load(self._slot())
        return self.desc.default if v is None else v

    def update(self, value) -> None:
        self._save(self._slot(), value)

    def get_rows(self, slots: np.ndarray):
        return np.asarray(
            [self.value_at(int(s)) for s in np.asarray(slots).tolist()],
            dtype=object)

    def value_at(self, slot: int):
        v = self._load(slot)
        return self.desc.default if v is None else v

    def put_rows(self, slots: np.ndarray, values) -> None:
        vals = list(values)
        for s, v in zip(np.asarray(slots).tolist(), vals):
            self._save(int(s), v)


class SpillListState(_SpillStateBase, ListState):
    kind = "list"

    def get(self) -> list:
        v = self._load(self._slot())
        return [] if v is None else list(v)

    def add(self, value) -> None:
        slot = self._slot()
        cur = self._load(slot) or []
        cur.append(value)
        self._save(slot, cur)

    def update(self, values) -> None:
        self._save(self._slot(), list(values))

    def add_rows(self, slots: np.ndarray, values) -> None:
        vals = list(values)
        for s, v in zip(np.asarray(slots).tolist(), vals):
            cur = self._load(int(s)) or []
            cur.append(v)
            self._save(int(s), cur)

    def get_rows(self, slots: np.ndarray) -> List[list]:
        return [(self._load(int(s)) or []) for s in np.asarray(slots).tolist()]


class SpillMapState(_SpillStateBase, MapState):
    kind = "map"

    def _map(self, slot: int) -> dict:
        return self._load(slot) or {}

    def get(self, key):
        return self._map(self._slot()).get(key)

    def put(self, key, value) -> None:
        slot = self._slot()
        m = self._map(slot)
        m[key] = value
        self._save(slot, m)

    def put_all(self, mapping) -> None:
        slot = self._slot()
        m = self._map(slot)
        m.update(mapping)
        self._save(slot, m)

    def remove(self, key) -> None:
        slot = self._slot()
        m = self._map(slot)
        if key in m:
            del m[key]
            self._save(slot, m)

    def contains(self, key) -> bool:
        return key in self._map(self._slot())

    def items(self):
        return list(self._map(self._slot()).items())

    def keys(self):
        return list(self._map(self._slot()).keys())

    def values(self):
        return list(self._map(self._slot()).values())

    def is_empty(self) -> bool:
        return not self._map(self._slot())


class SpillAggregatingState(_SpillStateBase, AggregatingState):
    """ACC pytrees pickled per slot; same AggregateFunction contract as the
    heap backend (identity/lift/combine/get_result, ``AggregateFunction.java:114``)."""

    kind = "aggregating"

    def __init__(self, backend, desc):
        _SpillStateBase.__init__(self, backend, desc)
        self.agg = getattr(desc, "agg", None) or getattr(desc, "reduce_fn")

    def _lift_rows(self, values):
        import jax
        lifted = self.agg.lift(values)
        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(lifted)]
        spec = self.agg.acc_spec()
        return [spec.unflatten([l[i] for l in leaves])
                for i in range(leaves[0].shape[0])]

    def _acc_to_np(self, acc):
        import jax
        return jax.tree_util.tree_map(np.asarray, acc)

    def add_rows(self, slots: np.ndarray, values) -> None:
        slots = np.asarray(slots)
        if not slots.size:
            return
        per_row = self._lift_rows(values)
        for s, lifted in zip(slots.tolist(), per_row):
            acc = self._load(int(s))
            if acc is None:
                acc = self.agg.identity()
            self._save(int(s), self._acc_to_np(self.agg.combine(acc, lifted)))

    def get_rows(self, slots: np.ndarray):
        """(results, alive) — same shape contract as the heap backend."""
        slots = np.asarray(slots)
        res = np.empty(slots.size, dtype=object)
        alive = np.zeros(slots.size, bool)
        for i, s in enumerate(slots.tolist()):
            acc = self._load(int(s))
            if acc is not None:
                res[i] = np.asarray(self.agg.get_result(acc))[()]
                alive[i] = True
        return res, alive

    def get(self):
        acc = self._load(self._slot())
        return None if acc is None else np.asarray(self.agg.get_result(acc))[()]

    def add(self, value) -> None:
        self.add_rows(np.array([self._slot()]), np.asarray([value]))


class SpillReducingState(SpillAggregatingState, ReducingState):
    """ReducingState == AggregatingState whose ACC is the value type."""

    kind = "reducing"




class PaneSpillStore:
    """Serialized per-(key, pane) pane-ring cells over the native SpillStore.

    The storage tier of the device-state paging subsystem
    (:mod:`flink_tpu.state.paging`): each entry is one cold key's
    accumulator cell for one pane, under the key ``struct('<qq', gid,
    pane)``.  The value layout is fixed-size and pickle-free so eviction /
    promotion round-trips are bit-exact and cheap::

        u8  flags   (bit0 = emit-mirror bit)
        i64 count   (element count of the cell)
        raw leaf bytes, one fixed-size block per ACC leaf in DEVICE
        dtype/shape (spec.leaf_dtypes / spec.leaf_shapes order)

    Device dtypes (not the host mirror's widened dtypes) on purpose: the
    paged tier must reproduce exactly what the HBM cell held, so a key that
    pages out and back in continues its accumulation history bitwise."""

    _HEADER = struct.Struct("<Bq")

    def __init__(self, directory: Optional[str] = None,
                 mem_budget: int = 64 << 20,
                 leaf_dtypes=(), leaf_shapes=()):
        self.directory = directory or tempfile.mkdtemp(
            prefix="flink_tpu_pages_")
        self.store = SpillStore(self.directory, mem_budget)
        self._closed = False
        self._dtypes = [np.dtype(d) for d in leaf_dtypes]
        self._shapes = [tuple(s) for s in leaf_shapes]
        self._counts_per_leaf = [int(np.prod(s)) if s else 1
                                 for s in self._shapes]
        self._sizes = [d.itemsize * c for d, c in
                       zip(self._dtypes, self._counts_per_leaf)]

    @staticmethod
    def _key(gid: int, pane: int) -> bytes:
        return struct.pack("<qq", gid, pane)

    def put(self, gid: int, pane: int, flags: int, count: int,
            leaf_values) -> None:
        parts = [self._HEADER.pack(flags, count)]
        for v, d, s in zip(leaf_values, self._dtypes, self._shapes):
            parts.append(np.ascontiguousarray(np.asarray(v, d)
                                              .reshape(s)).tobytes())
        self.store.put(self._key(gid, pane), b"".join(parts))

    def get(self, gid: int, pane: int):
        """(flags, count, [leaf arrays]) or None."""
        raw = self.store.get(self._key(gid, pane))
        if raw is None:
            return None
        flags, count = self._HEADER.unpack_from(raw)
        off = self._HEADER.size
        vals = []
        for d, s, c, sz in zip(self._dtypes, self._shapes,
                               self._counts_per_leaf, self._sizes):
            a = np.frombuffer(raw, d, count=c, offset=off)
            vals.append(a.reshape(s) if s else a[0])
            off += sz
        return flags, count, vals

    def delete(self, gid: int, pane: int) -> None:
        self.store.delete(self._key(gid, pane))

    def clear(self) -> None:
        if self._closed:
            return
        for k in list(self.store.keys()):
            self.store.delete(k)

    def __len__(self) -> int:
        return len(self.store)

    def mem_used(self) -> int:
        return 0 if self._closed else self.store.mem_used()

    def log_bytes(self) -> int:
        return 0 if self._closed else self.store.log_bytes()

    def close(self) -> None:
        # occupancy gauges may read stats after the operator closed: byte
        # gauges report 0 rather than touching a closed native handle
        self._closed = True
        self.store.close()


class SpillKeyedStateBackend:
    """Keyed state backend over the native spill store (RocksDB-tier analog).

    Drop-in for ``HeapKeyedStateBackend`` where state exceeds memory; the key
    index (slot ids) stays in memory — values spill.
    """

    def __init__(self, directory: Optional[str] = None,
                 max_parallelism: int = 128, mem_budget: int = 64 << 20,
                 clock: Callable[[], int] = _now_ms):
        self.max_parallelism = max_parallelism
        self.directory = directory or tempfile.mkdtemp(prefix="flink_tpu_spill_")
        self.mem_budget = mem_budget
        #: slot managed-memory claim (runtime/memory.py), once bound
        self._reservation = None
        self.store = SpillStore(self.directory, mem_budget)
        self._clock = clock
        self._index = None
        self._states: Dict[str, _SpillStateBase] = {}
        self._pending_restore: Dict[str, Dict[str, Any]] = {}
        self._current_slot = _ABSENT

    # -- keys (same contract as heap backend) -------------------------------
    @property
    def num_keys(self) -> int:
        return 0 if self._index is None else self._index.num_keys

    def _ensure_index(self, sample_key):
        if self._index is None:
            self._index = make_key_index(sample_key)
        return self._index

    def key_slots(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys)
        if keys.size == 0:
            return np.zeros(0, np.int32)
        return self._ensure_index(keys[0]).lookup_or_insert(keys)

    def set_current_key(self, key) -> None:
        self._current_slot = int(self.key_slots(np.asarray([key]))[0])

    def current_slot(self) -> int:
        return self._current_slot

    def slot_keys(self, slots: np.ndarray) -> np.ndarray:
        rev = self._index.reverse_keys()
        return np.asarray(rev)[np.asarray(slots)]

    # -- states --------------------------------------------------------------
    def get_state(self, desc: StateDescriptor):
        st = self._states.get(desc.name)
        if st is not None:
            return st
        if isinstance(desc, AggregatingStateDescriptor):
            st = SpillAggregatingState(self, desc)
        elif isinstance(desc, ReducingStateDescriptor):
            st = SpillReducingState(self, desc)
        elif isinstance(desc, state_api.ListStateDescriptor):
            st = SpillListState(self, desc)
        elif isinstance(desc, state_api.MapStateDescriptor):
            st = SpillMapState(self, desc)
        else:
            st = SpillValueState(self, desc)
        self._states[desc.name] = st
        pending = self._pending_restore.pop(desc.name, None)
        if pending is not None:
            st.restore(pending)
        return st

    def value_state(self, name: str, **kw) -> SpillValueState:
        return self.get_state(state_api.ValueStateDescriptor(name, **kw))

    def list_state(self, name: str, **kw) -> SpillListState:
        return self.get_state(state_api.ListStateDescriptor(name, **kw))

    def map_state(self, name: str, **kw) -> SpillMapState:
        return self.get_state(state_api.MapStateDescriptor(name, **kw))

    def reducing_state(self, name: str, reduce_fn, **kw) -> SpillReducingState:
        return self.get_state(state_api.ReducingStateDescriptor(name, reduce_fn, **kw))

    def aggregating_state(self, name: str, agg, **kw) -> SpillAggregatingState:
        return self.get_state(state_api.AggregatingStateDescriptor(name, agg, **kw))

    # -- snapshot / restore (repo-standard keyed snapshot format) ------------
    def snapshot(self) -> Dict[str, Any]:
        if self._index is None:
            return {"empty": True}
        n = self.num_keys
        snap: Dict[str, Any] = {
            "key_index": self._index.snapshot(),
            "key_index_kind": type(self._index).__name__,
            "num_keys": n,
            "backend": "spill",
            "state_names": sorted(set(self._states) | set(self._pending_restore)),
        }
        for name, st in self._states.items():
            for f, v in st.snapshot(n).items():
                snap[f"state.{name}.{f}"] = v
        for name, sub in self._pending_restore.items():
            for f, v in sub.items():
                snap[f"state.{name}.{f}"] = v
        return snap

    @staticmethod
    def row_fields(snap: Dict[str, Any]) -> List[str]:
        return [k for k in snap if k.startswith("state.")]

    def restore(self, snap: Dict[str, Any]) -> None:
        if snap.get("empty"):
            return
        kind = snap.get("key_index_kind", "KeyIndex")
        cls = ObjectKeyIndex if kind == "ObjectKeyIndex" else KeyIndex
        self._index = cls.restore(snap["key_index"])
        for name in snap.get("state_names", []):
            key = f"state.{name}.rows"
            if key not in snap:
                continue
            sub = {"rows": snap[key]}
            st = self._states.get(name)
            if st is None:
                # blob restore is kind-agnostic: write the store entries now,
                # real descriptor re-binds via get_state (same name)
                _SpillStateBase(self, state_api.StateDescriptor(name)).restore(sub)
            else:
                st.restore(sub)

    # -- durability ----------------------------------------------------------
    def persist(self) -> None:
        """fsync the spill log + manifest (local-recovery fast path)."""
        self.store.flush()

    def compact(self) -> int:
        return self.store.compact()

    def reserve_managed(self, manager, owner: str) -> None:
        """Claim this backend's resident-byte budget from the slot's
        managed memory (the RocksDB-tier reservation analog: the budget is
        accounted against the slot BEFORE the job runs, so an
        over-committed slot fails at open time, not as a mid-job OOM).
        Released by :meth:`close`."""
        if self._reservation is None and manager is not None:
            self._reservation = manager.reserve(owner, self.mem_budget)

    def close(self) -> None:
        if self._reservation is not None:
            self._reservation.release()
            self._reservation = None
        self.store.close()
