"""Key-group state redistribution: rescale keyed snapshots.

Analog of ``StateAssignmentOperation.java`` (``reDistributeKeyedStates:250``,
``createKeyGroupPartitions:615``): on restore at a different parallelism,
each new subtask receives exactly the rows whose key group falls in its
range.  Works on the snapshot convention shared by keyed operators here —
a ``key_index`` snapshot (slot -> raw key) plus row-indexed arrays aligned
with slot ids — so splitting is a vectorized mask/slice, and merging is
concat + re-index.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from flink_tpu.core import keygroups
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex

#: channel-state snapshot section versions this runtime reads.  v1 (PR 5)
#: records elements keyed by physical channel index only; v2 additionally
#: records per-input routing metadata (key column, partitioning, producer
#: max-parallelism, logical port), which is what makes rescale-time
#: redistribution possible.  Unknown versions still fail loudly.
CHANNEL_STATE_VERSIONS = (1, 2)
#: the version new snapshots are written at
CHANNEL_STATE_WRITE_VERSION = 2


class ChannelStateRescaleError(RuntimeError):
    """A snapshot carrying persisted in-flight CHANNEL STATE (an unaligned
    checkpoint) was handed to a path that cannot redistribute it.  v2
    sections (this runtime's write format) carry the per-input routing
    metadata needed to re-route each persisted element by its own key, so
    keyed rescale proceeds; a legacy v1 section with non-empty elements
    has no routing metadata — for those the supported procedure is still
    drain-then-rescale: take an ALIGNED savepoint (stop-with-savepoint, or
    let one aligned periodic checkpoint complete) and rescale from that."""


def reject_channel_state(snapshot, context: str) -> None:
    """Fail LOUDLY if any subtask snapshot in a job checkpoint carries
    non-empty unaligned channel state — paths that cannot redistribute
    (e.g. offline merges) must never silently drop or misroute persisted
    in-flight elements.  ``snapshot`` is the MiniCluster/ProcessCluster
    layout ``{uid: {"subtasks": [...]}}``.  The keyed RESCALE path no
    longer calls this: it redistributes v2 sections by record key
    (:func:`redistribute_channel_state`)."""
    if not isinstance(snapshot, dict):
        return
    for uid, entry in snapshot.items():
        if uid.startswith("__") or not isinstance(entry, dict):
            continue
        for idx, sub in enumerate(entry.get("subtasks", []) or []):
            if not isinstance(sub, dict):
                continue
            cs = sub.get("channel_state")
            elements = (cs.get("elements", []) if isinstance(cs, dict)
                        else cs)
            if elements:
                raise ChannelStateRescaleError(
                    f"{context}: subtask {uid}[{idx}] snapshot carries "
                    f"{len(elements)} persisted in-flight channel-state "
                    f"elements (unaligned checkpoint) — this path cannot "
                    f"redistribute channel state; drain-then-rescale: "
                    f"use an ALIGNED savepoint instead")


# ---------------------------------------------------------------------------
# channel-state redistribution (the FLIP-76 follow-on: rescale restores of
# unaligned checkpoints re-route persisted in-flight elements by KEY)
# ---------------------------------------------------------------------------

def _route_batch(el, info, new_parallelism: int):
    """One persisted in-flight RecordBatch -> ``[(target, sub_batch)]``,
    routed by the RECORD'S OWN KEY exactly the way the producing edge's
    dispatcher routes live batches: the batch's own ``key_groups`` when
    the upstream keying attached them, else the edge's key column hashed
    with the producer's max-parallelism (``KeyGroupStreamPartitioner``),
    then ``kg * P' // maxp`` — the same assignment
    ``core.keygroups.route_raw_keys`` computes.  Returns None when the
    element is not key-routable (non-keyed edge, no key metadata)."""
    kg = getattr(el, "key_groups", None)
    maxp = int(info.get("max_parallelism", 128)) if info else 128
    if kg is None:
        if not info or info.get("partitioning") != "hash" \
                or info.get("key_column") is None:
            return None
        keys = np.asarray(el.column(info["key_column"]))
        kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys), maxp)
    target = (np.asarray(kg, np.int64) * new_parallelism) // maxp
    out = []
    for t in range(new_parallelism):
        sel = target == t
        if sel.any():
            out.append((int(t), el.select(sel)))
    return out


def redistribute_channel_state(sections, new_parallelism: int,
                               context: str = "rescale"):
    """Persisted in-flight channel state across a parallelism change.

    ``sections``: the old subtasks' channel-state snapshot sections (one
    per old subtask, subtask order; None/missing entries allowed).
    Returns ``new_parallelism`` v2 sections whose elements are keyed by
    LOGICAL input port (``by_logical_port``): on restore each element
    replays into the first input channel of its port, BEFORE any new
    input — the same ordering contract same-parallelism restore has.

    Routing: each persisted RecordBatch splits row-wise by the record's
    own key into the new key-group ranges (``_route_batch``); non-keyed
    batches, watermarks and every other in-flight element replay on the
    downstream's subtask 0.  Ordering is deterministic: old subtasks in
    index order, each section's elements in recorded order, and a split
    batch's per-target slices preserve row order — so any one new
    subtask sees its share of the in-flight stream in the original
    relative order.

    Output sections are themselves re-redistributable: each carries an
    ``inputs`` list indexed by LOGICAL PORT with the original edges'
    routing metadata (key column, partitioning, producer
    max-parallelism), so a second pass — e.g. restoring a rewritten
    savepoint at yet another parallelism — routes every element exactly
    as the first did.  (Two edges sharing one logical port keep the
    first edge's metadata; batches that carry ``key_groups`` route by
    them regardless.)

    A legacy v1 section (no per-input routing metadata) with non-empty
    elements raises :class:`ChannelStateRescaleError` — old snapshots
    stay readable at the SAME parallelism, but keyed redistribution
    needs the v2 metadata."""
    out_elements = [[] for _ in range(new_parallelism)]
    port_infos: Dict[int, Dict[str, Any]] = {}
    unaligned = False
    align_ms = 0.0
    overtaken_total = 0
    for idx, sec in enumerate(sections):
        if not isinstance(sec, dict):
            if sec:
                raise ChannelStateRescaleError(
                    f"{context}: subtask {idx} carries a legacy bare-list "
                    f"channel-state section ({len(sec)} elements) — no "
                    f"routing metadata; drain-then-rescale instead")
            continue
        version = sec.get("version")
        elements = list(sec.get("elements", []))
        unaligned |= bool(sec.get("unaligned"))
        align_ms = max(align_ms, float(sec.get("alignment_ms", 0.0)))
        overtaken_total += int(sec.get("overtaken_bytes", 0))
        if not elements:
            continue
        if version not in CHANNEL_STATE_VERSIONS:
            raise ValueError(
                f"{context}: unknown channel-state snapshot version "
                f"{version!r} (this runtime reads "
                f"{list(CHANNEL_STATE_VERSIONS)})")
        if version < 2:
            raise ChannelStateRescaleError(
                f"{context}: subtask {idx} snapshot carries "
                f"{len(elements)} persisted in-flight elements in a v1 "
                f"channel-state section — v1 has no per-input routing "
                f"metadata, so it cannot be redistributed across "
                f"parallelisms; drain-then-rescale (ALIGNED savepoint), "
                f"or re-checkpoint on a v2 runtime first")
        inputs = sec.get("inputs") or []
        for i, el in elements:
            # in an already-redistributed section ``i`` IS the logical
            # port and ``inputs`` is port-indexed — the same lookup works
            info = inputs[i] if isinstance(i, int) and i < len(inputs) \
                and inputs[i] else None
            port = (int(info.get("logical", i if sec.get("by_logical_port")
                                  else 0)) if info
                    else (int(i) if sec.get("by_logical_port") else 0))
            if info and port not in port_infos:
                port_infos[port] = dict(info, logical=port)
            routed = (_route_batch(el, info, new_parallelism)
                      if el.is_batch() and len(el) else None)
            if routed is None:
                # non-keyed / broadcast in-flight element (or a control
                # element like a watermark): downstream subtask 0
                out_elements[0].append((port, el))
            else:
                for t, sub in routed:
                    out_elements[t].append((port, sub))
    from flink_tpu.cluster.channels import element_bytes
    max_port = max(port_infos, default=-1)
    port_inputs = [port_infos.get(p, {}) for p in range(max_port + 1)]
    out = []
    for t, els in enumerate(out_elements):
        persisted = sum(element_bytes(el) for _p, el in els)
        out.append({"version": CHANNEL_STATE_WRITE_VERSION,
                    "elements": els,
                    "by_logical_port": True,
                    "inputs": [dict(pi) for pi in port_inputs],
                    "persisted_bytes": int(persisted),
                    # the REAL overtake accounting of the input sections,
                    # carried on subtask 0 only so job-level sums (which
                    # add across subtasks) stay exact
                    "overtaken_bytes": overtaken_total if t == 0 else 0,
                    "alignment_ms": align_ms,
                    "unaligned": unaligned})
    return out


#: snapshot-kind dispatch shared by the rescale split
#: (``cluster/adaptive._split_member``) and the savepoint merge
#: (``state_processor/savepoint._merge_keyed_group``): ONE ordered
#: marker-key -> operator-class table, so a member's split and merge can
#: never dispatch to different operators (the kinds used to live as
#: parallel if-chains in three files).  First matching marker wins.
_SNAPSHOT_KINDS: Tuple[Tuple[str, str, str], ...] = (
    ("pane_base", "flink_tpu.operators.window_agg", "WindowAggOperator"),
    ("session_keys", "flink_tpu.operators.session_window",
     "SessionWindowOperator"),
    ("nfas", "flink_tpu.cep.operator", "CepOperator"),
    ("two_phase", "flink_tpu.connectors.sinks", "TwoPhaseCommitSink"),
)


def snapshot_operator_class(member: Any):
    """The operator class owning this member snapshot's rescale
    ``split_snapshot``/``merge_snapshots`` pair, or None for generic
    keyed / opaque members.  Imports lazily (operators must stay
    importable without this module's callers)."""
    import importlib

    if not isinstance(member, dict):
        return None
    for key, mod, cls in _SNAPSHOT_KINDS:
        if key in member:
            return getattr(importlib.import_module(mod), cls)
    return None


def _restore_index(snap: Dict[str, Any]):
    cls = (ObjectKeyIndex if snap.get("key_index_kind") == "ObjectKeyIndex"
           else KeyIndex)
    return cls.restore(snap["key_index"] if "key_index" in snap else snap["keys"])


def _index_snapshot_of(keys: np.ndarray, kind: str):
    """Build a fresh index over ``keys``; returns (snapshot, row_order) where
    ``row_order[slot]`` is the position in ``keys`` owning that slot.  Slot
    assignment within one insert batch is NOT input order (hash-probe order),
    so row arrays must be permuted by ``row_order`` to stay slot-aligned."""
    idx = ObjectKeyIndex() if kind == "ObjectKeyIndex" else KeyIndex()
    n = len(keys)
    if n:
        slots = idx.lookup_or_insert(np.asarray(keys))
        row_order = np.empty(n, np.int64)
        row_order[slots] = np.arange(n)
    else:
        row_order = np.zeros(0, np.int64)
    return idx.snapshot(), row_order


def _row_select(value, sel: np.ndarray):
    if isinstance(value, (list, tuple)):
        out = [np.asarray(v)[sel] for v in value]
        return type(value)(out) if isinstance(value, tuple) else out
    return np.asarray(value)[sel]


def _row_concat(values: List[Any]):
    first = values[0]
    if isinstance(first, (list, tuple)):
        out = [np.concatenate([np.asarray(v[i]) for v in values])
               for i in range(len(first))]
        return type(first)(out) if isinstance(first, tuple) else out
    return np.concatenate([np.asarray(v) for v in values])


def split_keyed_snapshot(snap: Dict[str, Any], row_fields: Sequence[str],
                         max_parallelism: int,
                         new_parallelism: int) -> List[Dict[str, Any]]:
    """One keyed-operator snapshot -> ``new_parallelism`` snapshots, rows
    routed by key-group range (same ranges the runtime assigns subtasks)."""
    if snap.get("empty") or "key_index" not in snap and "keys" not in snap:
        return [dict(snap) for _ in range(new_parallelism)]
    idx = _restore_index(snap)
    keys = np.asarray(idx.reverse_keys())
    kind = snap.get("key_index_kind", type(idx).__name__)
    kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                      max_parallelism)
    ranges = keygroups.key_group_ranges(max_parallelism, new_parallelism)
    out = []
    for r in ranges:
        sel = np.nonzero((kg >= r.start) & (kg <= r.end))[0]
        sub = dict(snap)
        key_field = "key_index" if "key_index" in snap else "keys"
        idx_snap, row_order = _index_snapshot_of(keys[sel], kind)
        sub[key_field] = idx_snap
        sub["key_index_kind"] = kind
        rows = sel[row_order]  # original row per new slot
        for f in row_fields:
            if f in snap and snap[f] is not None:
                sub[f] = _row_select(snap[f], rows)
        out.append(sub)
    return out


def merge_keyed_snapshots(snaps: Sequence[Dict[str, Any]],
                          row_fields: Sequence[str]) -> Dict[str, Any]:
    """Inverse of ``split_keyed_snapshot`` (scale-down / savepoint compaction)."""
    live = [s for s in snaps
            if not s.get("empty") and ("key_index" in s or "keys" in s)]
    if not live:
        return dict(snaps[0]) if snaps else {"empty": True}
    key_field = "key_index" if "key_index" in live[0] else "keys"
    all_keys = []
    for s in live:
        idx = _restore_index(s)
        all_keys.append(np.asarray(idx.reverse_keys()))
    keys = np.concatenate(all_keys)
    kind = live[0].get("key_index_kind", "KeyIndex")
    merged = dict(live[0])
    idx_snap, row_order = _index_snapshot_of(keys, kind)
    merged[key_field] = idx_snap
    merged["key_index_kind"] = kind
    for f in row_fields:
        if f in live[0] and live[0][f] is not None:
            merged[f] = _row_select(_row_concat([s[f] for s in live]), row_order)
    return merged
