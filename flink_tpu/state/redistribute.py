"""Key-group state redistribution: rescale keyed snapshots.

Analog of ``StateAssignmentOperation.java`` (``reDistributeKeyedStates:250``,
``createKeyGroupPartitions:615``): on restore at a different parallelism,
each new subtask receives exactly the rows whose key group falls in its
range.  Works on the snapshot convention shared by keyed operators here —
a ``key_index`` snapshot (slot -> raw key) plus row-indexed arrays aligned
with slot ids — so splitting is a vectorized mask/slice, and merging is
concat + re-index.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from flink_tpu.core import keygroups
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex


class ChannelStateRescaleError(RuntimeError):
    """A snapshot carrying persisted in-flight CHANNEL STATE (an unaligned
    checkpoint) was handed to the rescale path.  Channel state is keyed by
    physical channel index, not key group — redistributing it across a
    different parallelism would replay in-flight elements into the wrong
    subtasks (duplicates and losses at once).  The supported procedure is
    drain-then-rescale: take an ALIGNED savepoint (stop-with-savepoint, or
    let one aligned periodic checkpoint complete) and rescale from that."""


def reject_channel_state(snapshot, context: str) -> None:
    """Fail LOUDLY if any subtask snapshot in a job checkpoint carries
    non-empty unaligned channel state — rescaling must never silently drop
    or misroute persisted in-flight elements.  ``snapshot`` is the
    MiniCluster/ProcessCluster layout ``{uid: {"subtasks": [...]}}``."""
    if not isinstance(snapshot, dict):
        return
    for uid, entry in snapshot.items():
        if uid.startswith("__") or not isinstance(entry, dict):
            continue
        for idx, sub in enumerate(entry.get("subtasks", []) or []):
            if not isinstance(sub, dict):
                continue
            cs = sub.get("channel_state")
            elements = (cs.get("elements", []) if isinstance(cs, dict)
                        else cs)
            if elements:
                raise ChannelStateRescaleError(
                    f"{context}: subtask {uid}[{idx}] snapshot carries "
                    f"{len(elements)} persisted in-flight channel-state "
                    f"elements (unaligned checkpoint) — channel state "
                    f"cannot be redistributed across parallelisms; "
                    f"drain-then-rescale: rescale from an ALIGNED "
                    f"savepoint instead")


def _restore_index(snap: Dict[str, Any]):
    cls = (ObjectKeyIndex if snap.get("key_index_kind") == "ObjectKeyIndex"
           else KeyIndex)
    return cls.restore(snap["key_index"] if "key_index" in snap else snap["keys"])


def _index_snapshot_of(keys: np.ndarray, kind: str):
    """Build a fresh index over ``keys``; returns (snapshot, row_order) where
    ``row_order[slot]`` is the position in ``keys`` owning that slot.  Slot
    assignment within one insert batch is NOT input order (hash-probe order),
    so row arrays must be permuted by ``row_order`` to stay slot-aligned."""
    idx = ObjectKeyIndex() if kind == "ObjectKeyIndex" else KeyIndex()
    n = len(keys)
    if n:
        slots = idx.lookup_or_insert(np.asarray(keys))
        row_order = np.empty(n, np.int64)
        row_order[slots] = np.arange(n)
    else:
        row_order = np.zeros(0, np.int64)
    return idx.snapshot(), row_order


def _row_select(value, sel: np.ndarray):
    if isinstance(value, (list, tuple)):
        out = [np.asarray(v)[sel] for v in value]
        return type(value)(out) if isinstance(value, tuple) else out
    return np.asarray(value)[sel]


def _row_concat(values: List[Any]):
    first = values[0]
    if isinstance(first, (list, tuple)):
        out = [np.concatenate([np.asarray(v[i]) for v in values])
               for i in range(len(first))]
        return type(first)(out) if isinstance(first, tuple) else out
    return np.concatenate([np.asarray(v) for v in values])


def split_keyed_snapshot(snap: Dict[str, Any], row_fields: Sequence[str],
                         max_parallelism: int,
                         new_parallelism: int) -> List[Dict[str, Any]]:
    """One keyed-operator snapshot -> ``new_parallelism`` snapshots, rows
    routed by key-group range (same ranges the runtime assigns subtasks)."""
    if snap.get("empty") or "key_index" not in snap and "keys" not in snap:
        return [dict(snap) for _ in range(new_parallelism)]
    idx = _restore_index(snap)
    keys = np.asarray(idx.reverse_keys())
    kind = snap.get("key_index_kind", type(idx).__name__)
    kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                      max_parallelism)
    ranges = keygroups.key_group_ranges(max_parallelism, new_parallelism)
    out = []
    for r in ranges:
        sel = np.nonzero((kg >= r.start) & (kg <= r.end))[0]
        sub = dict(snap)
        key_field = "key_index" if "key_index" in snap else "keys"
        idx_snap, row_order = _index_snapshot_of(keys[sel], kind)
        sub[key_field] = idx_snap
        sub["key_index_kind"] = kind
        rows = sel[row_order]  # original row per new slot
        for f in row_fields:
            if f in snap and snap[f] is not None:
                sub[f] = _row_select(snap[f], rows)
        out.append(sub)
    return out


def merge_keyed_snapshots(snaps: Sequence[Dict[str, Any]],
                          row_fields: Sequence[str]) -> Dict[str, Any]:
    """Inverse of ``split_keyed_snapshot`` (scale-down / savepoint compaction)."""
    live = [s for s in snaps
            if not s.get("empty") and ("key_index" in s or "keys" in s)]
    if not live:
        return dict(snaps[0]) if snaps else {"empty": True}
    key_field = "key_index" if "key_index" in live[0] else "keys"
    all_keys = []
    for s in live:
        idx = _restore_index(s)
        all_keys.append(np.asarray(idx.reverse_keys()))
    keys = np.concatenate(all_keys)
    kind = live[0].get("key_index_kind", "KeyIndex")
    merged = dict(live[0])
    idx_snap, row_order = _index_snapshot_of(keys, kind)
    merged[key_field] = idx_snap
    merged["key_index_kind"] = kind
    for f in row_fields:
        if f in live[0] and live[0][f] is not None:
            merged[f] = _row_select(_row_concat([s[f] for s in live]), row_order)
    return merged
