"""Native (C++) write-through window mirror — the fire/mirror/probe hot path.

Python face of the ``WinMirror`` kernels in ``native/flink_native.cc``: the
host emit tier of :class:`~flink_tpu.operators.window_agg.WindowAggOperator`
keeps a write-through host value mirror of the device ACC cells so window
fires ship zero device->host bytes (decisive on egress-constrained links).
Round 3 ran that mirror in numpy (per-batch ``bincount``/``reduceat`` plus a
per-fire gather cascade); these kernels move the whole inner loop native:

- ``probe_update`` fuses the key-index probe and the mirror write-through
  into ONE C pass per micro-batch (the (slot, pane, value) triples are
  computed once and consumed twice), sharing the key dict with the Python
  :class:`~flink_tpu.state.keyindex.KeyIndex` so slot ids agree with the
  device state rows by construction.
- ``fire`` is one sequential C sweep that combines the window's panes,
  compacts non-empty rows, and resolves raw keys — fire cost becomes memory
  bandwidth instead of Python/numpy time.

This is the same make-the-inner-loop-native move as the reference's Cython
fast coders (``pyflink/fn_execution/table/window_aggregate_fast.pyx:51``)
applied to ``WindowOperator.processElement``/``emitWindowContents``
(``WindowOperator.java:300,574``).

Eligibility: scalar accumulator leaves, add/min/max combine kinds, an int64
native key index.  Anything else falls back to the numpy mirror in
``window_agg.py`` (same semantics, slower).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: numpy dtype -> native value-load tag (VDt in flink_native.cc)
_VDT = {np.dtype(np.float64): 0, np.dtype(np.float32): 1,
        np.dtype(np.int64): 2, np.dtype(np.int32): 3}
_KINDS = {"add": 0, "min": 1, "max": 2}


def auto_shards() -> int:
    """Default shard count for the native probe: one shard per core up to
    4 (the pass is memory-latency bound — beyond a few cores the misses in
    flight saturate the memory controller, and oversubscribing steals CPU
    from XLA's own thread pool).  ``FLINK_TPU_NATIVE_SHARDS`` overrides."""
    env = os.environ.get("FLINK_TPU_NATIVE_SHARDS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    cores = 0
    from flink_tpu.native import get_lib
    lib = get_lib()
    if lib is not None and hasattr(lib, "fn_hw_threads"):
        cores = int(lib.fn_hw_threads())  # what the C worker pool sees
    return max(1, min(4, cores or os.cpu_count() or 1))


_calibrated_shards: Optional[int] = None
#: module-scope: lazily creating the lock would itself be a check-then-act
#: race between the first two calibrating threads
_calib_lock = threading.Lock()


def measure_fused_probe(lib, shards: int, n_keys: int, B: int,
                        keys_all: np.ndarray, vals_all: np.ndarray,
                        rounds: int = 3) -> float:
    """Best-of-``rounds`` wall seconds of the fused C probe+fold at
    ``shards`` over a warm ``n_keys`` table — the shared measurement
    harness of the native-shards A/B and the device-probe calibration
    (state/device_keyindex).  ``keys_all``/``vals_all`` hold ``rounds``
    consecutive batches of ``B``.  The throwaway keydict/mirror pair is
    released via try/finally even on a mid-measurement failure."""
    import time
    d = lib.keydict_create(2 * n_keys)
    h = None
    try:
        kind = (ctypes.c_uint8 * 1)(0)   # add
        lt = (ctypes.c_uint8 * 1)(0)     # f64 storage
        init = np.zeros(1, np.uint64)
        h = lib.wm_create(d, 1, kind, lt,
                          init.ctypes.data_as(ctypes.c_void_p))
        vdt = (ctypes.c_uint8 * 1)(1)    # VF32 input
        warm_k = np.arange(n_keys, dtype=np.int64)
        warm_p = np.zeros(n_keys, np.int64)
        warm_v = np.zeros(n_keys, np.float32)
        warm_s = np.empty(n_keys, np.int32)
        vptr = (ctypes.c_void_p * 1)(warm_v.ctypes.data)
        lib.wm_probe_update(h, warm_k.ctypes.data, warm_p.ctypes.data,
                            n_keys, vptr, vdt, warm_s.ctypes.data,
                            0, 0, 0, 0, shards)
        panes = np.zeros(B, np.int64)
        slots = np.empty(B, np.int32)
        best = float("inf")
        for i in range(rounds):
            k = np.ascontiguousarray(keys_all[i * B:(i + 1) * B])
            v = np.ascontiguousarray(vals_all[i * B:(i + 1) * B])
            vp = (ctypes.c_void_p * 1)(v.ctypes.data)
            t0 = time.perf_counter()
            lib.wm_probe_update(h, k.ctypes.data, panes.ctypes.data, B,
                                vp, vdt, slots.ctypes.data, 0, 0, 0, 0,
                                shards)
            best = min(best, time.perf_counter() - t0)
        return best
    finally:
        if h:
            lib.wm_destroy(h)
        lib.keydict_destroy(d)


def calibrated_shards() -> int:
    """MEASURED default shard count, cached process-wide: A/Bs the fused
    probe serially vs at :func:`auto_shards` on a throwaway keydict+mirror
    (~tens of ms, once per process) and returns the faster setting.  The
    core count alone cannot be trusted — on shared or steal-heavy vCPUs a
    single core's prefetch pipelining already saturates the memory
    subsystem and extra shards lose — so this is the shard twin of the
    device-sync transport calibration: measure, don't assume.  Explicit
    ``FLINK_TPU_NATIVE_SHARDS`` (via auto_shards) short-circuits the
    measurement."""
    global _calibrated_shards
    if _calibrated_shards is not None:
        return _calibrated_shards
    with _calib_lock:
        if _calibrated_shards is not None:
            return _calibrated_shards
        auto = auto_shards()
        if os.environ.get("FLINK_TPU_NATIVE_SHARDS"):
            _calibrated_shards = auto  # explicit: trust the operator
            return auto
        from flink_tpu.native import get_lib
        lib = get_lib()
        if auto <= 1 or lib is None or not hasattr(lib, "wm_create"):
            _calibrated_shards = 1
            return 1
        n_keys = 1 << 15
        B = 1 << 15  # >= the C pass's parallel threshold
        rng = np.random.default_rng(17)
        keys_all = np.ascontiguousarray(
            rng.integers(0, n_keys, 3 * B).astype(np.int64))
        vals_all = np.ascontiguousarray(
            rng.random(3 * B).astype(np.float32))
        timings = {shards: measure_fused_probe(lib, shards, n_keys, B,
                                               keys_all, vals_all)
                   for shards in (1, auto)}
        _calibrated_shards = min(timings, key=timings.get)
        return _calibrated_shards


class NativeWindowMirror:
    """ctypes handle to a C++ WinMirror sharing a KeyIndex's key dict."""

    def __init__(self, lib, key_index, handle, mirror_dtypes):
        self._lib = lib
        #: pins the KeyIndex (and thus the shared keydict) for our lifetime
        self._key_index = key_index
        self._h = handle
        self._mirror_dtypes = tuple(np.dtype(d) for d in mirror_dtypes)
        #: reusable fire output buffers (keys, counts, leaves) — a 1M-key
        #: fire would otherwise first-touch ~24MB of fresh pages per window
        self._fire_scratch = None
        #: reusable export buffers (counts, leaves) for the same reason;
        #: snapshots run inside the checkpointed hot path
        self._export_scratch = None

    @classmethod
    def try_create(cls, key_index, spec, kinds: Optional[Sequence[str]],
                   mirror_dtypes) -> Optional["NativeWindowMirror"]:
        """A mirror for this (key index, ACC spec), or None if ineligible."""
        from flink_tpu.native import get_lib

        lib = get_lib()
        dict_handle = getattr(key_index, "_handle", None)
        if lib is None or not hasattr(lib, "wm_create") or not dict_handle:
            return None
        if kinds is None or not all(k in _KINDS for k in kinds):
            return None
        if any(tuple(s) != () for s in spec.leaf_shapes):
            return None  # non-scalar leaves: numpy mirror handles them
        mdts = [np.dtype(d) for d in mirror_dtypes]
        if any(d not in (np.dtype(np.float64), np.dtype(np.int64))
               for d in mdts):
            return None
        nl = spec.num_leaves
        kind_b = (ctypes.c_uint8 * nl)(*[_KINDS[k] for k in kinds])
        lt_b = (ctypes.c_uint8 * nl)(
            *[1 if d == np.dtype(np.int64) else 0 for d in mdts])
        init = np.empty(nl, np.uint64)
        for j, (iv, d) in enumerate(zip(spec.leaf_inits, mdts)):
            init[j] = np.asarray(iv).astype(d).reshape(1).view(np.uint64)[0]
        h = lib.wm_create(dict_handle, nl, kind_b, lt_b,
                          init.ctypes.data_as(ctypes.c_void_p))
        if not h:
            return None
        return cls(lib, key_index, h, mdts)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.wm_destroy(h)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
            self._h = None

    # -- hot path ------------------------------------------------------------
    def probe_update(self, keys: np.ndarray, panes: np.ndarray,
                     lifted: List[np.ndarray], pane_mod: int = 0,
                     flat_out: Optional[np.ndarray] = None,
                     flat_fill: int = 0, shards: int = 1,
                     shard_div: int = 0,
                     shard_ns: Optional[np.ndarray] = None) -> np.ndarray:
        """Fused probe + mirror fold; returns int32 slot ids for the device
        scatter.  ``lifted`` is the agg's host_lift leaves, one [B] array per
        ACC leaf.  When ``flat_out`` (int32[>=n], contiguous) is given, the C
        pass also writes the device scatter ids slot * pane_mod +
        pane %% pane_mod into it — one pass instead of three numpy ops —
        and fills the padding tail flat_out[n:] with ``flat_fill`` (the
        dropped-row id), so a pow2 staging buffer comes back dispatch-ready.
        ``shards`` > 1 partitions the fold across the native worker pool
        (disjoint slot ownership, no locks) — results are bit-identical to
        the serial pass at any shard count.  Ownership defaults to
        slot %% shards classes; ``shard_div`` > 0 switches to CONTIGUOUS
        slot ranges [t*shard_div, (t+1)*shard_div) — the mesh runtime
        passes K_cap / n_devices so probe shard t owns exactly the
        key-group range whose device state block lives on mesh device t.
        ``shard_ns`` (int64[>=shards], contiguous) receives each shard's
        fold wall time in nanoseconds (the per-shard probe breakdown)."""
        keys = np.ascontiguousarray(keys, np.int64)
        panes = np.ascontiguousarray(panes, np.int64)
        n = keys.size
        slots = np.empty(n, np.int32)
        if n == 0:
            if flat_out is not None:
                flat_out[:] = flat_fill
            if shard_ns is not None:
                shard_ns[:] = 0
            return slots
        nl = len(self._mirror_dtypes)
        arrs = []
        vdt = (ctypes.c_uint8 * nl)()
        for j, l in enumerate(lifted):
            a = np.ascontiguousarray(l)
            if a.dtype not in _VDT:
                a = a.astype(np.float64)
            arrs.append(a)
            vdt[j] = _VDT[a.dtype]
        vals = (ctypes.c_void_p * nl)(*[a.ctypes.data for a in arrs])
        flat_ptr = 0
        flat_cap = 0
        if flat_out is not None:
            # hard checks (not asserts): a wrong buffer here is C-side
            # memory corruption, and pane_mod 0 is a divide-by-zero in C
            if (flat_out.dtype != np.int32 or not flat_out.flags.c_contiguous
                    or flat_out.size < n or pane_mod <= 0):
                raise ValueError(
                    "flat_out must be contiguous int32 with size >= n and "
                    "pane_mod > 0")
            flat_ptr = flat_out.ctypes.data
            flat_cap = flat_out.size
        ns_ptr = 0
        if shard_ns is not None:
            if (shard_ns.dtype != np.int64
                    or not shard_ns.flags.c_contiguous
                    or shard_ns.size < max(1, int(shards))):
                raise ValueError("shard_ns must be contiguous int64 with "
                                 "size >= shards")
            shard_ns[:] = 0
            ns_ptr = shard_ns.ctypes.data
        self._lib.wm_probe_update2(
            self._h, keys.ctypes.data, panes.ctypes.data, n, vals, vdt,
            slots.ctypes.data, pane_mod, flat_ptr, flat_cap,
            int(flat_fill), max(1, int(shards)), int(shard_div), ns_ptr)
        return slots

    def apply_delta(self, pane: int, counts: np.ndarray,
                    leaves: List[np.ndarray]) -> None:
        """Fold a pane-granular DELTA (warm-key contributions accumulated on
        the device by the device-resident key probe) into the mirror:
        counts add, each leaf combines by its declared kind.  Delta rows are
        identity-initialized, so untouched rows fold as no-ops."""
        counts = np.ascontiguousarray(counts, np.int64)
        nl = len(self._mirror_dtypes)
        arrs = []
        vdt = (ctypes.c_uint8 * nl)()
        for j, l in enumerate(leaves):
            a = np.ascontiguousarray(l)
            if a.dtype not in _VDT:
                a = a.astype(np.float64)
            arrs.append(a)
            vdt[j] = _VDT[a.dtype]
        ptrs = (ctypes.c_void_p * nl)(*[a.ctypes.data for a in arrs])
        self._lib.wm_apply_delta(self._h, int(pane), counts.size,
                                 counts.ctypes.data, ptrs, vdt)

    def fire(self, panes: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray]]:
        """Combine+compact the window's panes: (keys[m], counts[m],
        leaf arrays [m]) in ascending slot order."""
        n = self._key_index.num_keys
        panes = np.ascontiguousarray(panes, np.int64)
        if n == 0 or panes.size == 0:
            return (np.empty(0, np.int64), np.empty(0, np.int64),
                    [np.empty(0, d) for d in self._mirror_dtypes])
        sc = self._fire_scratch
        if sc is None or sc[0].size < n:
            cap = 1 << max(10, (n - 1).bit_length())
            sc = self._fire_scratch = (
                np.empty(cap, np.int64), np.empty(cap, np.int64),
                [np.empty(cap, d) for d in self._mirror_dtypes])
        out_keys, out_counts, out_leaves = sc
        ptrs = (ctypes.c_void_p * len(out_leaves))(
            *[a.ctypes.data for a in out_leaves])
        m = int(self._lib.wm_fire(self._h, panes.ctypes.data, panes.size,
                                  out_keys.ctypes.data,
                                  out_counts.ctypes.data, ptrs))
        # keys/leaves COPY out (they outlive this call in emitted batches);
        # counts are consumed-or-dropped by the caller, so a view suffices
        return (out_keys[:m].copy(), out_counts[:m],
                [a[:m].copy() for a in out_leaves])

    # -- pane lifecycle ------------------------------------------------------
    def drop_pane(self, pane: int) -> None:
        self._lib.wm_drop_pane(self._h, int(pane))

    def live_panes(self) -> np.ndarray:
        k = int(self._lib.wm_pane_count(self._h))
        out = np.empty(k, np.int64)
        if k:
            self._lib.wm_live_panes(self._h, out.ctypes.data)
        out.sort()
        return out

    # -- snapshots -----------------------------------------------------------
    def export_pane(self, pane: int, nrows: int
                    ) -> Tuple[bool, np.ndarray, List[np.ndarray]]:
        """(exists, counts[nrows] int64, leaf columns in mirror dtypes).

        Returns VIEWS into reusable scratch (overwritten by the next
        export): callers (snapshot column fill, verify) consume them
        before exporting the next pane."""
        sc = self._export_scratch
        if sc is None or sc[0].size < nrows:
            cap = 1 << max(10, (nrows - 1).bit_length())
            sc = self._export_scratch = (
                np.empty(cap, np.int64),
                [np.empty(cap, d) for d in self._mirror_dtypes])
        counts, leaves = sc[0], sc[1]
        ptrs = (ctypes.c_void_p * len(leaves))(
            *[a.ctypes.data for a in leaves])
        ex = int(self._lib.wm_export_pane(self._h, int(pane), nrows,
                                          counts.ctypes.data, ptrs))
        return bool(ex), counts[:nrows], [a[:nrows] for a in leaves]

    def import_pane(self, pane: int, counts: np.ndarray,
                    leaves: List[np.ndarray]) -> None:
        counts = np.ascontiguousarray(counts, np.int64)
        arrs = [np.ascontiguousarray(l, d)
                for l, d in zip(leaves, self._mirror_dtypes)]
        ptrs = (ctypes.c_void_p * len(arrs))(*[a.ctypes.data for a in arrs])
        self._lib.wm_import_pane(self._h, int(pane), counts.size,
                                 counts.ctypes.data, ptrs)
