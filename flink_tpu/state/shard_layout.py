"""Sharding-aware keyed-state layout: one logical state, per-shard slices.

The mesh-sharded ``WindowAggOperator`` (``parallel/mesh_runtime.py``) keeps
its ``[K, P, *leaf]`` pane ring physically split over a 1-D device mesh:
device ``d`` owns the CONTIGUOUS key-slot block ``[d*K/D, (d+1)*K/D)`` —
the key-group ranges of ``KeyGroupRangeAssignment.java:50-84`` mapped onto
mesh positions (``parallel/mesh.py``).  This module is the snapshot face of
that layout: instead of one dense gid-indexed array per state field, a
mesh snapshot carries **per-shard slices with key-group-range manifests**,
so that

- each shard's slice is produced from (and restores into) exactly the rows
  its device owns — no cross-shard gather is required to WRITE a snapshot,
- a snapshot taken at N shards restores at M shards (either direction,
  M == 1 included) by re-slicing the manifest ranges, the
  ``StateAssignmentOperation.reDistributeKeyedStates`` story, and
- every existing dense-format consumer (cluster rescale via
  ``state/redistribute.py``, savepoint tooling, the single-chip operator)
  keeps working through :func:`densify_keyed_snapshot`, which merges the
  slices back into the dense layout on first touch.

The slices tile ``[0, num_keys)`` in ascending shard order, so merging is a
plain concatenation and splitting is a plain row-slice — the layout never
reorders keys, which is what keeps fire digests and rescale bit-exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

#: snapshot keys introduced by the sharded layout
SLICES_KEY = "shard_slices"
LAYOUT_KEY = "shard_layout"

#: state fields sliced along the key-slot axis (leaves is a LIST of arrays,
#: each sliced on axis 0)
_ROW_FIELDS = ("counts", "leaves")


@dataclass(frozen=True)
class ShardLayout:
    """Key-slot ownership of a 1-D mesh: shard ``d`` owns rows
    ``[d * K // D, (d+1) * K // D)`` of the ``[K, ...]`` state arrays
    (``K`` divisible by ``D`` — the operator rounds capacity up)."""

    n_shards: int
    K: int

    def __post_init__(self):
        if self.n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if self.K % self.n_shards:
            raise ValueError(
                f"key capacity {self.K} not divisible by {self.n_shards} "
                f"shards (round K up first)")

    @property
    def rows_per_shard(self) -> int:
        return self.K // self.n_shards

    def row_range(self, shard: int) -> Tuple[int, int]:
        kd = self.rows_per_shard
        return shard * kd, (shard + 1) * kd

    def shard_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Owning shard per global row id (clamped: out-of-range sentinel
        rows map onto the last shard, whose scatter drops them anyway)."""
        return np.minimum(np.asarray(rows, np.int64) // self.rows_per_shard,
                          self.n_shards - 1).astype(np.int32)

    def key_group_range(self, shard: int,
                        max_parallelism: int = 128) -> Tuple[int, int]:
        """The contiguous key-group range owned by ``shard`` under the
        reference assignment formula (manifest metadata)."""
        from flink_tpu.core import keygroups
        r = keygroups.key_group_ranges(max_parallelism, self.n_shards)[shard]
        return int(r.start), int(r.end)

    def route_keys(self, keys: np.ndarray,
                   max_parallelism: int = 128) -> np.ndarray:
        """Owning shard per RAW key — the record route (key hash -> murmur
        key group -> contiguous range), the SAME implementation the
        queryable tier's client-side routing uses
        (``core/keygroups.route_raw_keys``): a client that partitions a
        lookup batch with this function lands every key on the server
        that owns its state."""
        from flink_tpu.core.keygroups import route_raw_keys
        return route_raw_keys(keys, self.n_shards, max_parallelism)


def split_to_shard_slices(snap: Dict[str, Any], layout: ShardLayout,
                          max_parallelism: int = 128) -> Dict[str, Any]:
    """Dense gid-indexed snapshot -> per-shard slices + manifest.

    The dense ``counts``/``leaves`` arrays cover rows ``[0, n)`` (live keys
    in global slot order); shard ``d``'s slice is the intersection of its
    row block with ``[0, n)`` — empty blocks (shards past the live keys)
    produce zero-row slices so the manifest always lists every shard."""
    snap = dict(snap)
    counts = snap.pop("counts")
    leaves = snap.pop("leaves")
    n = int(counts.shape[0])
    slices: List[Dict[str, Any]] = []
    for d in range(layout.n_shards):
        lo, hi = layout.row_range(d)
        lo, hi = min(lo, n), min(hi, n)
        slices.append({
            "shard": d,
            "row_range": (int(lo), int(hi)),
            "key_groups": layout.key_group_range(d, max_parallelism),
            "counts": np.asarray(counts[lo:hi]),
            "leaves": [np.asarray(l[lo:hi]) for l in leaves],
        })
    snap[SLICES_KEY] = slices
    snap[LAYOUT_KEY] = {"n_shards": layout.n_shards, "K": layout.K,
                        "max_parallelism": int(max_parallelism),
                        "num_keys": n}
    return snap


def densify_keyed_snapshot(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Merge per-shard slices back into the dense gid-indexed layout.

    No-op (returns ``snap`` unchanged) for snapshots already in the dense
    format, so every restore/rescale path can call it unconditionally.
    Slices may arrive out of order (e.g. after a round trip through a
    coordinator that aggregates per-subtask acks); they are re-tiled by
    their manifest row ranges and must cover ``[0, num_keys)`` exactly."""
    if SLICES_KEY not in snap:
        return snap
    snap = dict(snap)
    slices = snap.pop(SLICES_KEY)
    meta = snap.pop(LAYOUT_KEY, None) or {}
    ordered = sorted(slices, key=lambda s: s["row_range"][0])
    n = int(meta.get("num_keys",
                     max((s["row_range"][1] for s in ordered), default=0)))
    expect = 0
    for s in ordered:
        lo, hi = s["row_range"]
        if lo != expect:
            raise ValueError(
                f"shard slices do not tile [0, {n}): gap/overlap at row "
                f"{expect} (next slice starts at {lo})")
        expect = hi
    if expect != n:
        raise ValueError(f"shard slices cover [0, {expect}) but the "
                         f"manifest says {n} keys")
    live = [s for s in ordered if s["counts"].shape[0]]
    if not live:
        first = ordered[0]
        snap["counts"] = np.asarray(first["counts"])
        snap["leaves"] = [np.asarray(l) for l in first["leaves"]]
        return snap
    snap["counts"] = np.concatenate([s["counts"] for s in live], axis=0)
    snap["leaves"] = [
        np.concatenate([s["leaves"][j] for s in live], axis=0)
        for j in range(len(live[0]["leaves"]))]
    return snap


def has_shard_slices(snap: Dict[str, Any]) -> bool:
    return SLICES_KEY in snap


def slice_manifest(snap: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The manifest rows (shard, row_range, key_groups) without the data —
    observability/REST surface."""
    return [{k: s[k] for k in ("shard", "row_range", "key_groups")}
            for s in snap.get(SLICES_KEY, ())]
