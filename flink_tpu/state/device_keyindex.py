"""Device-resident key index: probe warm keys INSIDE the jitted step.

The host C probe+mirror fold (``wm_probe_update2``) is the one remaining
hot-path wall (~70% of elapsed on the 1M-key tumbling-sum bench): every
batch, every record pays a random host-memory probe plus a mirror fold.
The reference pays this as a per-record hash probe in
``CopyOnWriteStateMap.java``; our batched analog can do what Flink never
could — resolve warm keys *on the accelerator, inside the already-
dispatched XLA step*, so the host pass touches only misses.

This module holds the device half of that split:

- An **open-addressing int64 -> int32 hash table as device arrays**: two
  int32 key planes (lo/hi words — jax runs with x64 disabled, so int64
  never rides the device) plus a ``slot + 1`` plane whose zero state IS the
  empty table (the same trick as the native ``KeyDict``).  Bucket starts
  come from the SAME splitmix64 ``_mix64`` family as
  :mod:`flink_tpu.state.keyindex`, computed on the host as one streaming
  vectorized pass (no random access, no insert — the wall is the probe
  walk + fold, not the hash), so slot ids agree with the host KeyIndex by
  construction.
- :func:`lax_probe` — the pure-lax vectorized probe loop (the portable,
  bit-identical fallback; tier-1 runs it under ``JAX_PLATFORMS=cpu``).
- :func:`pallas_probe` — an optional Pallas TPU kernel behind
  :func:`pallas_probe_available` (TPU backend + importable pallas + table
  fits VMEM); same arithmetic, same results.
- :class:`DeviceKeyIndex` — the host-side owner: a numpy occupancy shadow
  decides insert buckets (the device table is only ever written by our
  scatters, so shadow and table cannot diverge), ``ensure_loaded`` bulk-
  inserts whatever tail of the KeyIndex the table is missing (initial
  load, restore, and per-batch miss inserts are all the same code path),
  and capacity is a **sticky pow2 high-water** so growth cannot recompile
  the consuming step more than O(log) times per run.
- :func:`calibrated_device_probe` — the measured A/B (device probe + fold
  dispatch vs the fused C pass) behind ``--device-probe auto``; the same
  measure-don't-assume pattern as the device-sync transport calibration
  and ``native_mirror.calibrated_shards``.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Optional, Tuple

import numpy as np

from flink_tpu.state.keyindex import _mix64

#: probe miss marker in the slot output
MISS = np.int32(-1)


# ---------------------------------------------------------------------------
# host-side helpers: key split + bucket starts (streaming, no random access)
# ---------------------------------------------------------------------------

def split_keys(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int64 keys -> (lo, hi) int32 word planes (device-safe under x64-off)."""
    u = np.ascontiguousarray(keys, np.int64).view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
    hi = (u >> np.uint64(32)).astype(np.uint32).view(np.int32)
    return lo, hi


def probe_starts(keys: np.ndarray, capacity: int) -> np.ndarray:
    """Bucket start per key: ``_mix64(key) & (capacity - 1)`` as int32."""
    h = _mix64(np.ascontiguousarray(keys, np.int64).view(np.uint64))
    return (h & np.uint64(capacity - 1)).astype(np.int64).astype(np.int32)


# ---------------------------------------------------------------------------
# probe implementations (device side)
# ---------------------------------------------------------------------------

def lax_probe(tab_lo, tab_hi, tab_slot1, key_lo, key_hi, start):
    """Vectorized open-addressing probe: returns int32 slots, -1 = miss.

    One ``while_loop`` round gathers every still-pending record's bucket;
    hits resolve to ``slot1 - 1``, empty buckets resolve to miss, occupied-
    by-another-key records step to the next bucket.  Load factor <= 0.5
    keeps expected rounds ~2 and the loop bound is the longest probe chain.
    """
    import jax.numpy as jnp
    from jax import lax

    cap = tab_slot1.shape[0]
    maskv = jnp.int32(cap - 1)

    def cond(state):
        pending, _idx, _slot = state
        return jnp.any(pending)

    def body(state):
        pending, idx, slot = state
        b_s = tab_slot1[idx]
        b_lo = tab_lo[idx]
        b_hi = tab_hi[idx]
        empty = b_s == 0
        hit = (~empty) & (b_lo == key_lo) & (b_hi == key_hi)
        slot = jnp.where(pending & hit, b_s - 1, slot)
        pending = pending & ~(hit | empty)
        idx = jnp.where(pending, (idx + 1) & maskv, idx)
        return pending, idx, slot

    pending0 = jnp.ones(start.shape, bool)
    slot0 = jnp.full(start.shape, MISS, jnp.int32)
    _p, _i, slot = lax.while_loop(cond, body, (pending0, start, slot0))
    return slot


#: Pallas opt-out (set FLINK_TPU_DEVICE_PROBE_PALLAS=0 to pin the lax path
#: on TPU); the capability check below gates it on by default when legal
_PALLAS_ENV = "FLINK_TPU_DEVICE_PROBE_PALLAS"

#: VMEM budget for the whole table (3 int32 planes) — beyond this the
#: blocks would not fit next to the batch tiles and the lax path (XLA
#: gather from HBM) is the right tool anyway
_PALLAS_VMEM_TABLE_BYTES = 8 << 20


def pallas_probe_available(capacity: int) -> bool:
    """True iff the Pallas TPU probe kernel is usable here: TPU backend,
    importable pallas, table planes fit the VMEM budget, not opted out."""
    if os.environ.get(_PALLAS_ENV, "1") in ("0", "off", "false"):
        return False
    try:
        import jax
        if jax.default_backend() != "tpu":
            return False
        from jax.experimental import pallas as pl          # noqa: F401
        from jax.experimental.pallas import tpu as pltpu   # noqa: F401
    except Exception:  # noqa: BLE001 — any import/backend issue: fall back
        return False
    return capacity * 12 <= _PALLAS_VMEM_TABLE_BYTES


def pallas_probe(tab_lo, tab_hi, tab_slot1, key_lo, key_hi, start):
    """Pallas TPU probe kernel: table planes pinned whole in VMEM, batch
    processed as one block, the same probe arithmetic as :func:`lax_probe`
    (bit-identical results).  Only called when
    :func:`pallas_probe_available` said yes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cap = int(tab_slot1.shape[0])

    def kernel(lo_ref, hi_ref, s1_ref, klo_ref, khi_ref, st_ref, out_ref):
        t_lo = lo_ref[:]
        t_hi = hi_ref[:]
        t_s1 = s1_ref[:]
        klo = klo_ref[:]
        khi = khi_ref[:]
        idx = st_ref[:]
        maskv = jnp.int32(cap - 1)

        def cond(state):
            pending, _i, _s = state
            return jnp.any(pending)

        def body(state):
            pending, i, s = state
            b_s = t_s1[i]
            empty = b_s == 0
            hit = (~empty) & (t_lo[i] == klo) & (t_hi[i] == khi)
            s = jnp.where(pending & hit, b_s - 1, s)
            pending = pending & ~(hit | empty)
            i = jnp.where(pending, (i + 1) & maskv, i)
            return pending, i, s

        pending0 = jnp.ones(idx.shape, bool)
        slot0 = jnp.full(idx.shape, MISS, jnp.int32)
        _p, _i, slot = jax.lax.while_loop(cond, body,
                                          (pending0, idx, slot0))
        out_ref[:] = slot

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(start.shape, jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 6,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(tab_lo, tab_hi, tab_slot1, key_lo, key_hi, start)


def probe_impl(capacity: int):
    """(name, fn) for this table capacity: the Pallas kernel when capable,
    else the pure-lax fallback — chosen at trace time, so the consuming
    jitted step bakes the right path in."""
    if pallas_probe_available(capacity):
        return "pallas", pallas_probe
    return "lax", lax_probe


# ---------------------------------------------------------------------------
# fused probe + scatter fold (the Pallas path beyond the probe, ISSUE-11)
# ---------------------------------------------------------------------------

#: Pallas fused probe+fold opt-out (FLINK_TPU_FUSED_PALLAS=0 pins the
#: probe-then-XLA-scatter path on TPU); the capability check gates it on
_FUSED_PALLAS_ENV = "FLINK_TPU_FUSED_PALLAS"

#: VMEM budget for table planes PLUS the flat delta planes: the fused
#: kernel pins both whole, so it serves small-state jobs (the probe-only
#: kernel plus an XLA scatter is the right tool past this)
_PALLAS_VMEM_FUSED_BYTES = 12 << 20


def pallas_probe_fold_available(capacity: int, flat_state: int,
                                kinds) -> bool:
    """True iff the fused Pallas probe+scatter-fold kernel is usable: TPU
    backend + importable pallas (the probe's own gate), a single scalar
    ``add`` accumulator leaf (the dominant sum-over-floats shape — the C
    pass fast-paths exactly the same case), and table + flat f64/i32 delta
    planes together inside the VMEM budget.  Same check/override pattern
    as ``pallas_probe``."""
    if os.environ.get(_FUSED_PALLAS_ENV, "1") in ("0", "off", "false"):
        return False
    if kinds is None or tuple(kinds) != ("add",):
        return False
    if not pallas_probe_available(capacity):
        return False
    return capacity * 12 + flat_state * 12 <= _PALLAS_VMEM_FUSED_BYTES


def pallas_probe_fold(tab_lo, tab_hi, tab_slot1, key_lo, key_hi, start,
                      pane_slots, b, vals, dsum, dcnt, pane_mod: int):
    """Fused Pallas TPU kernel: probe + delta scatter-fold in ONE kernel —
    the round trip through HBM between the probe's slot output and the
    fold's gather/scatter input disappears.  ``dsum``/``dcnt`` are the
    FLAT ``[K*P]`` delta planes, aliased in-place; ``b`` is the valid-row
    count as an int32[1] plane (rows past it, and probe misses, fold
    nothing).  Returns (slot, new_dsum, new_dcnt) with arithmetic
    identical to ``probe`` + ``ops.scatter.scatter_fold_counts`` — the
    lax path the tier-1 digests pin."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cap = int(tab_slot1.shape[0])
    Bp = int(start.shape[0])

    def kernel(lo_ref, hi_ref, s1_ref, klo_ref, khi_ref, st_ref, ps_ref,
               b_ref, v_ref, sum_ref, cnt_ref, slot_ref, osum_ref,
               ocnt_ref):
        t_lo = lo_ref[:]
        t_hi = hi_ref[:]
        t_s1 = s1_ref[:]
        klo = klo_ref[:]
        khi = khi_ref[:]
        idx = st_ref[:]
        maskv = jnp.int32(cap - 1)

        def cond(state):
            pending, _i, _s = state
            return jnp.any(pending)

        def pbody(state):
            pending, i, s = state
            b_s = t_s1[i]
            empty = b_s == 0
            hit = (~empty) & (t_lo[i] == klo) & (t_hi[i] == khi)
            s = jnp.where(pending & hit, b_s - 1, s)
            pending = pending & ~(hit | empty)
            i = jnp.where(pending, (i + 1) & maskv, i)
            return pending, i, s

        pending0 = jnp.ones(idx.shape, bool)
        slot0 = jnp.full(idx.shape, MISS, jnp.int32)
        _p, _i, slot = jax.lax.while_loop(cond, pbody,
                                          (pending0, idx, slot0))
        slot_ref[:] = slot
        osum_ref[:] = sum_ref[:]
        ocnt_ref[:] = cnt_ref[:]
        bb = b_ref[0]
        ps = ps_ref[:]
        vv = v_ref[:]
        flat = slot * jnp.int32(pane_mod) + ps

        def fbody(k, carry):
            @pl.when((k < bb) & (slot[k] >= 0))
            def _fold():
                f = flat[k]
                osum_ref[f] = osum_ref[f] + vv[k].astype(osum_ref.dtype)
                ocnt_ref[f] = ocnt_ref[f] + 1

            return carry

        jax.lax.fori_loop(0, Bp, fbody, 0)

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((Bp,), jnp.int32),
                   jax.ShapeDtypeStruct(dsum.shape, dsum.dtype),
                   jax.ShapeDtypeStruct(dcnt.shape, dcnt.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 11,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        input_output_aliases={9: 1, 10: 2},
    )(tab_lo, tab_hi, tab_slot1, key_lo, key_hi, start, pane_slots, b,
      vals, dsum, dcnt)


# ---------------------------------------------------------------------------
# DeviceKeyIndex — host-side owner of the device table
# ---------------------------------------------------------------------------

class DeviceKeyIndex:
    """Device twin of a :class:`~flink_tpu.state.keyindex.KeyIndex`.

    The KeyIndex (host C keydict) stays the slot-id authority; this class
    keeps a device-resident probe table in lockstep via ``ensure_loaded``:
    whatever tail of slots the table has not seen yet is placed in the host
    occupancy shadow (vectorized, the same linear probing the device walk
    runs) and shipped as ONE scatter.  The device never inserts, so shadow
    and table cannot diverge.  Capacity is a sticky pow2 high-water —
    growth rebuilds shadow + table at the doubled size and recompiles the
    consuming step once per capacity, never per batch.
    """

    def __init__(self, initial_capacity: int = 1 << 16,
                 max_load: float = 0.5, sharding=None):
        cap = 1 << 10
        while cap < initial_capacity:
            cap <<= 1
        self._max_load = max_load
        self._sharding = sharding
        self._n = 0               # slots loaded into the table
        self._alloc(cap)

    # -- internals ----------------------------------------------------------
    def _alloc(self, cap: int) -> None:
        import jax
        import jax.numpy as jnp

        self.capacity = cap
        self._shadow_used = np.zeros(cap, bool)
        lo = jnp.zeros(cap, jnp.int32)
        hi = jnp.zeros(cap, jnp.int32)
        s1 = jnp.zeros(cap, jnp.int32)
        if self._sharding is not None:
            lo = jax.device_put(lo, self._sharding)
            hi = jax.device_put(hi, self._sharding)
            s1 = jax.device_put(s1, self._sharding)
        self.tab_lo, self.tab_hi, self.tab_slot1 = lo, hi, s1
        self._insert_fn = self._make_insert_fn()

    def _make_insert_fn(self):
        import jax

        sharding = self._sharding

        def insert(tab_lo, tab_hi, tab_slot1, buckets, klo, khi, slot1):
            new_lo = tab_lo.at[buckets].set(klo, mode="drop")
            new_hi = tab_hi.at[buckets].set(khi, mode="drop")
            new_s1 = tab_slot1.at[buckets].set(slot1, mode="drop")
            if sharding is not None:
                from jax.lax import with_sharding_constraint as wsc
                new_lo = wsc(new_lo, sharding)
                new_hi = wsc(new_hi, sharding)
                new_s1 = wsc(new_s1, sharding)
            return new_lo, new_hi, new_s1

        return jax.jit(insert, donate_argnums=(0, 1, 2))

    def _place(self, keys: np.ndarray) -> np.ndarray:
        """Claim one shadow bucket per (unique) key via the device's own
        linear probing; returns the bucket indices.  Vectorized rounds:
        same-bucket races resolve by first-in-batch, losers re-probe."""
        n = keys.size
        buckets = np.full(n, -1, np.int64)
        idx = probe_starts(keys, self.capacity).astype(np.int64)
        pending = np.arange(n, dtype=np.int64)
        pidx = idx
        maskv = np.int64(self.capacity - 1)
        while pending.size:
            free = ~self._shadow_used[pidx]
            f_pend = pending[free]
            f_idx = pidx[free]
            if f_pend.size:
                win_idx, first = np.unique(f_idx, return_index=True)
                w_pend = f_pend[first]
                self._shadow_used[win_idx] = True
                buckets[w_pend] = win_idx
            unresolved = buckets[pending] < 0
            pending = pending[unresolved]
            pidx = (pidx[unresolved] + 1) & maskv
        return buckets

    # -- public -------------------------------------------------------------
    @property
    def num_loaded(self) -> int:
        return self._n

    def table(self):
        return self.tab_lo, self.tab_hi, self.tab_slot1

    def prepare_batch(self, keys: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(key_lo, key_hi, start) int32 planes for one batch — the only
        per-record host work of the device probe: streaming hash + split,
        no random access, no insert."""
        lo, hi = split_keys(keys)
        return lo, hi, probe_starts(keys, self.capacity)

    def ensure_loaded(self, key_index) -> int:
        """Bring the device table up to date with ``key_index``: insert
        slots [num_loaded, num_keys) — initial bulk load, restore reload,
        and per-batch miss inserts are all this one path.  Returns the
        number of newly inserted keys."""
        n = int(key_index.num_keys)
        if n == self._n:
            return 0
        if n < self._n:
            # the key index was reset/restored under us: rebuild from empty
            self._n = 0
            self._alloc(self.capacity)
        if n > int(self.capacity * self._max_load):
            self._grow(n)
        rev = np.asarray(key_index.reverse_keys(), np.int64)
        new_keys = rev[self._n:n]
        buckets = self._place(new_keys)
        slots1 = np.arange(self._n + 1, n + 1, dtype=np.int32)
        self._upload(buckets, new_keys, slots1)
        inserted = n - self._n
        self._n = n
        return inserted

    def _upload(self, buckets: np.ndarray, keys: np.ndarray,
                slots1: np.ndarray) -> None:
        import jax.numpy as jnp
        from flink_tpu.ops.shapes import next_pow2

        m = buckets.size
        mp = next_pow2(max(m, 1), 64)   # bounded compile count
        b_p = np.full(mp, self.capacity, np.int32)   # pads: out of range
        b_p[:m] = buckets
        lo, hi = split_keys(keys)
        lo_p = np.zeros(mp, np.int32)
        hi_p = np.zeros(mp, np.int32)
        s1_p = np.zeros(mp, np.int32)
        lo_p[:m] = lo
        hi_p[:m] = hi
        s1_p[:m] = slots1
        self.tab_lo, self.tab_hi, self.tab_slot1 = self._insert_fn(
            self.tab_lo, self.tab_hi, self.tab_slot1,
            jnp.asarray(b_p), jnp.asarray(lo_p), jnp.asarray(hi_p),
            jnp.asarray(s1_p))

    def _grow(self, needed: int) -> None:
        """Sticky pow2 growth: double until ``needed`` fits the load
        factor, re-place every loaded key, upload the rebuilt table."""
        cap = self.capacity
        while needed > int(cap * self._max_load):
            cap <<= 1
        if cap == self.capacity:
            return
        loaded = self._n
        # keys currently in the table, in slot order, from the shadow-
        # independent source of truth we are mirroring: re-derive from the
        # caller at the next ensure_loaded — here we must rebuild NOW, so
        # read the old planes back (cheap relative to a rehash; growth is
        # O(log) per run)
        old_lo = np.asarray(self.tab_lo)
        old_hi = np.asarray(self.tab_hi)
        old_s1 = np.asarray(self.tab_slot1)
        occ = old_s1 > 0
        keys_u = (old_lo[occ].view(np.uint32).astype(np.uint64)
                  | (old_hi[occ].view(np.uint32).astype(np.uint64)
                     << np.uint64(32)))
        keys = keys_u.view(np.int64)
        slots1 = old_s1[occ]
        self._alloc(cap)
        self._n = loaded
        if keys.size:
            buckets = self._place(keys)
            self._upload(buckets, keys, slots1)


# ---------------------------------------------------------------------------
# measured A/B calibration (the --device-probe auto verdict)
# ---------------------------------------------------------------------------

_calibrated_probe: Optional[bool] = None
_calib_lock = threading.Lock()

#: env override: "on"/"off" skip the measurement ("auto" measures)
_ENV = "FLINK_TPU_DEVICE_PROBE"


def calibrated_device_probe() -> bool:
    """MEASURED verdict, cached process-wide: does the device-resident
    probe + delta fold beat the fused host C pass on THIS backend?  A/Bs a
    warm 32k-key table over three real-sized batches — the probe twin of
    the device-sync transport calibration and the native-shards A/B:
    measure, don't assume (on CPU the XLA scatter's ~0.5µs/update usually
    loses to the C fold; on a real accelerator the fold rides the already-
    dispatched step).  ``FLINK_TPU_DEVICE_PROBE=on|off`` short-circuits."""
    global _calibrated_probe
    if _calibrated_probe is not None:
        return _calibrated_probe
    with _calib_lock:
        if _calibrated_probe is not None:
            return _calibrated_probe
        env = os.environ.get(_ENV, "").lower()
        if env in ("on", "1", "true"):
            _calibrated_probe = True
            return True
        if env in ("off", "0", "false"):
            _calibrated_probe = False
            return False
        _calibrated_probe = _measure_device_probe()
        return _calibrated_probe


def _measure_device_probe() -> bool:
    import time

    import jax
    import jax.numpy as jnp

    from flink_tpu.native import get_lib
    lib = get_lib()
    if lib is None or not hasattr(lib, "wm_create"):
        # no native fused pass to beat: the host fallback is numpy — the
        # device probe wins by default wherever it is eligible at all
        return True
    n_keys = 1 << 15
    B = 1 << 15
    rng = np.random.default_rng(23)
    keys_all = np.ascontiguousarray(
        rng.integers(0, n_keys, 3 * B).astype(np.int64))
    vals_all = np.ascontiguousarray(rng.random(3 * B).astype(np.float32))

    # ---- host side: the fused C probe+fold at the shard count the real
    # fallback path would USE (calibrated_shards — measuring the serial
    # pass on a host whose calibration picked 4 shards would bias the A/B
    # toward the device)
    from flink_tpu.state.native_mirror import (calibrated_shards,
                                               measure_fused_probe)
    host_best = measure_fused_probe(lib, calibrated_shards(), n_keys, B,
                                    keys_all, vals_all)

    # ---- device side: probe + f64 delta fold dispatch (warm table)
    from flink_tpu.state.keyindex import KeyIndex
    ki = KeyIndex(initial_capacity=2 * n_keys)
    ki.lookup_or_insert(np.arange(n_keys, dtype=np.int64))
    dki = DeviceKeyIndex(initial_capacity=2 * n_keys)
    dki.ensure_loaded(ki)
    _name, probe = probe_impl(dki.capacity)

    @jax.jit
    def step(tab_lo, tab_hi, tab_s1, dsum, dcnt, klo, khi, start, vals):
        slot = probe(tab_lo, tab_hi, tab_s1, klo, khi, start)
        hit = slot >= 0
        ids = jnp.where(hit, slot, jnp.int32(np.iinfo(np.int32).max))
        new_sum = dsum.at[ids].add(vals.astype(dsum.dtype), mode="drop")
        new_cnt = dcnt.at[ids].add(1, mode="drop")
        miss = jnp.sum(~hit, dtype=jnp.int32)
        return new_sum, new_cnt, miss

    # the real delta fold accumulates in f64 (the mirror's precision) —
    # measure the same thing; enable_x64 scopes the wide dtype per-trace
    from jax.experimental import enable_x64
    with enable_x64():
        dsum = jnp.zeros(n_keys, jnp.float64)
        dcnt = jnp.zeros(n_keys, jnp.int32)
        dev_best = float("inf")
        for i in range(3):
            k = keys_all[i * B:(i + 1) * B]
            v = vals_all[i * B:(i + 1) * B]
            # the per-batch host hashing (prepare_batch) is part of the
            # device path's real cost: time it inside the sample
            t0 = time.perf_counter()
            klo, khi, start = dki.prepare_batch(k)
            dsum, dcnt, miss = step(*dki.table(), dsum, dcnt,
                                    jnp.asarray(klo), jnp.asarray(khi),
                                    jnp.asarray(start), jnp.asarray(v))
            jax.block_until_ready(dcnt)
            dt = time.perf_counter() - t0
            if i > 0:   # first timed round still pays compile: skip it
                dev_best = min(dev_best, dt)
    return dev_best < host_best
