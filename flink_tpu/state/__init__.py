

def make_keyed_backend(config=None, max_parallelism: int = 128,
                       directory=None):
    """Construct the configured keyed state backend (StateBackendOptions
    analog): 'hbm'/'heap' -> dense-row heap backend, 'spill' -> the native
    C++ spill tier, 'changelog' / 'changelog-spill' -> the changelog wrapper
    over the chosen inner backend."""
    from flink_tpu.config.options import StateOptions
    from flink_tpu.state.heap import HeapKeyedStateBackend

    name = "hbm"
    if config is not None:
        name = (config.get(StateOptions.BACKEND) or "hbm").lower()
    if name in ("hbm", "heap", "host"):
        return HeapKeyedStateBackend(max_parallelism=max_parallelism)
    if name == "spill":
        from flink_tpu.state.spill import SpillKeyedStateBackend
        return SpillKeyedStateBackend(directory, max_parallelism=max_parallelism)
    if name in ("changelog", "changelog-heap"):
        from flink_tpu.state.changelog import ChangelogKeyedStateBackend
        return ChangelogKeyedStateBackend(
            HeapKeyedStateBackend(max_parallelism=max_parallelism))
    if name == "changelog-spill":
        from flink_tpu.state.changelog import ChangelogKeyedStateBackend
        from flink_tpu.state.spill import SpillKeyedStateBackend
        return ChangelogKeyedStateBackend(
            SpillKeyedStateBackend(directory, max_parallelism=max_parallelism))
    raise ValueError(f"unknown state.backend {name!r}; "
                     f"use hbm|spill|changelog|changelog-spill")
