"""Batch WordCount over the DataSet API (flink-examples batch flagship).

    python examples/wordcount_batch.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from flink_tpu.dataset import ExecutionEnvironment

TEXT = """to be or not to be that is the question whether tis nobler in
the mind to suffer the slings and arrows of outrageous fortune""".split()


def main():
    env = ExecutionEnvironment.get_execution_environment()
    counts = (env.from_columns({"word": np.asarray(TEXT, object)})
              .group_by("word").count()
              .sort_partition("count", ascending=False))
    for row in counts.first_n(5).collect():
        print(f"{row['word']}: {row['count']}")


if __name__ == "__main__":
    main()
