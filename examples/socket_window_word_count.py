"""SocketWindowWordCount — the reference's flagship example
(flink-examples/.../SocketWindowWordCount.java:69-84, baseline config #1):
  socket text -> split words -> keyBy(word) -> 5s tumbling window -> count.

Run a text server first (e.g. ``nc -lk 9999``), then:

    python -m flink_tpu run examples/socket_window_word_count.py
"""

import numpy as np


def main(env):
    from flink_tpu.windowing.assigners import TumblingProcessingTimeWindows

    def split_words(cols):
        words, src = [], []
        for i, line in enumerate(np.asarray(cols["line"]).tolist()):
            for w in line.split():
                words.append(w)
                src.append(i)
        return {"word": np.asarray(words, object)}, np.asarray(src, np.int64)

    (env.socket_text_stream("localhost", 9999)
        .flat_map(split_words)
        .key_by("word")
        .window(TumblingProcessingTimeWindows.of(5000))
        .count()
        .print())
