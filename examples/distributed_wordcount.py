"""Multi-process cluster example: keyed sum across worker processes with
periodic checkpoints and automatic restart on worker loss.

Run:  python examples/distributed_wordcount.py
The job ships as this module's ``build`` function (the jar analog): every
worker imports it and deploys its assigned subtask slice; cross-process
edges ride credit-controlled TCP channels.
"""

import numpy as np


def build():
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    n = 100_000
    words = (np.arange(n) % 1000).astype(np.int64)   # 1000 distinct "words"
    (env.from_collection(columns={"word": words, "one": np.ones(n)},
                         batch_size=1024)
        .key_by("word")
        .sum("one", output_column="count")
        .collect())
    return env.get_stream_graph("distributed-wordcount")


if __name__ == "__main__":
    import os
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))  # repo root (flink_tpu)
    sys.path.insert(0, here)                   # this module (job shipping)
    from flink_tpu.cluster.distributed import ProcessCluster
    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage

    store = FileCheckpointStorage(tempfile.mkdtemp(prefix="flink-tpu-ckpt-"))
    pc = ProcessCluster(
        "distributed_wordcount:build", n_workers=2,
        checkpoint_storage=store, checkpoint_interval_ms=500,
        restart_attempts=2,
        extra_sys_path=(here, os.path.dirname(here)))
    res = pc.run(timeout_s=300)
    final = {}
    for r in res["rows"]:
        final[r["word"]] = r["count"]
    print(f"state={res['state']} attempts={res['attempts']} "
          f"checkpoints={len(res['completed_checkpoints'])} "
          f"words={len(final)} total={sum(final.values()):.0f}")
