"""Fraud detection walkthrough (flink-walkthroughs analog), rebased onto
the GATED scenario definition (ISSUE-15): the pattern and the CEP
topology are imported from ``flink_tpu.scenarios.fraud_detection`` —
the same bait/strike detection that ``bench.py --scenario
fraud_detection`` runs under the diurnal load curve with chaos at the
peak — so the shipped example and the gated workload cannot diverge.

    python -m flink_tpu run examples/fraud_detection.py

A SMALL "bait" transaction immediately followed by a LARGE "strike" on
the same account raises an alert; alerts print and collect.
"""

import numpy as np


def main(env):
    from flink_tpu.scenarios.fraud_detection import (LARGE_MIN, SMALL_MAX,
                                                     detect_frauds)

    rng = np.random.default_rng(7)
    n = 10_000
    accounts = rng.integers(0, 50, n).astype(np.int64)
    # legitimate traffic sits strictly between the thresholds
    amounts = SMALL_MAX + rng.random(n) * (LARGE_MIN - SMALL_MAX)
    ts = np.arange(n, dtype=np.int64)
    # plant bait -> strike sequences for three accounts
    for acct, pos in ((7, 100), (21, 2000), (33, 7777)):
        accounts[pos] = accounts[pos + 1] = acct
        amounts[pos] = 0.5          # bait
        amounts[pos + 1] = 900.0    # strike

    tx = (env.from_collection(columns={"account": accounts,
                                       "amount": amounts,
                                       "t": ts}, batch_size=1024)
          .assign_timestamps_and_watermarks(0, timestamp_column="t")
          .key_by("account"))
    # the scenario's CEP stage: Pattern(small -> large within 4 windows)
    alerts = detect_frauds(tx, window_ms=1000, amount_column="amount")
    alerts.print(prefix="ALERT")
    return alerts.collect()
