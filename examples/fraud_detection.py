"""Fraud detection walkthrough analog (flink-walkthroughs): a keyed process
function with state + timers flags accounts whose small transaction is
followed by a large one within a time window, emitting alerts to a side
output.

    python -m flink_tpu run examples/fraud_detection.py
"""

import numpy as np


def main(env):
    from flink_tpu.core.batch import OutputTag
    from flink_tpu.operators.process import KeyedProcessFunction
    from flink_tpu.state.api import ValueStateDescriptor

    alerts = OutputTag("alerts")

    class Detector(KeyedProcessFunction):
        def process_batch(self, ctx, batch):
            flagged = ctx.state(ValueStateDescriptor("small_seen", default=0))
            seen, _ = flagged.get_rows(batch.key_ids)
            amounts = np.asarray(batch.column("amount"))
            small = amounts < 1.0
            big = amounts > 500.0
            fraud = big & (np.asarray(seen) == 1)
            if fraud.any():
                ctx.side_output(alerts, {
                    "account": np.asarray(batch.column("account"))[fraud],
                    "amount": amounts[fraud]})
            flagged.put_rows(batch.key_ids, np.where(small, 1, 0))
            return [batch]

    rng = np.random.default_rng(7)
    n = 10_000
    amounts = rng.random(n) * 100
    amounts[rng.integers(0, n, 20)] = 0.5       # bait
    amounts[rng.integers(0, n, 20)] = 900.0     # strike
    tx = env.from_collection(columns={
        "account": rng.integers(0, 50, n),
        "amount": amounts})
    scored = tx.key_by("account").process(Detector())
    scored.get_side_output(alerts).print(prefix="ALERT")
    scored.collect()
