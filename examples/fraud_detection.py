"""Fraud detection walkthrough analog (flink-walkthroughs): a keyed process
function with state flags accounts whose SMALL transaction is immediately
followed by a LARGE one, emitting alerts to a side output.

    python -m flink_tpu run examples/fraud_detection.py
"""

import numpy as np


def main(env):
    from flink_tpu.core.batch import OutputTag
    from flink_tpu.operators.process import KeyedProcessFunction
    from flink_tpu.state.api import ValueStateDescriptor

    alerts = OutputTag("alerts")
    SMALL, LARGE = 1.0, 500.0

    class Detector(KeyedProcessFunction):
        def process_batch(self, ctx, batch):
            flagged = ctx.state(ValueStateDescriptor("small_seen", default=0))
            accounts = np.asarray(batch.column("account"))
            amounts = np.asarray(batch.column("amount"))
            carried, _ = flagged.get_rows(batch.key_ids)
            carried = np.asarray(carried).astype(bool)
            # sequential per-account scan WITHIN the batch (the per-record
            # order matters for this pattern), seeded by the carried state
            last_small = {}
            fraud = np.zeros(len(batch), bool)
            for i, (acct, amt) in enumerate(zip(accounts.tolist(),
                                                amounts.tolist())):
                prev = last_small.get(acct, carried[i])
                fraud[i] = prev and amt > LARGE
                last_small[acct] = amt < SMALL
            if fraud.any():
                ctx.side_output(alerts, {"account": accounts[fraud],
                                         "amount": amounts[fraud]})
            # persist each account's LAST small-flag for the next batch
            final = np.asarray([last_small[a] for a in accounts.tolist()],
                               np.int64)
            flagged.put_rows(batch.key_ids, final)
            return [batch]

    rng = np.random.default_rng(7)
    n = 10_000
    accounts = rng.integers(0, 50, n)
    amounts = rng.random(n) * 100
    # plant bait -> strike sequences for three accounts
    for acct, pos in ((7, 100), (21, 2000), (33, 7777)):
        accounts[pos] = accounts[pos + 1] = acct
        amounts[pos] = 0.5          # bait
        amounts[pos + 1] = 900.0    # strike

    tx = env.from_collection(columns={"account": accounts,
                                      "amount": amounts}, batch_size=1024)
    scored = tx.key_by("account").process(Detector())
    scored.get_side_output(alerts).print(prefix="ALERT")
    scored.collect()
