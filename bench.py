"""North-star benchmark: 1M-key tumbling windowed sum (BASELINE.json).

Measures records/sec/chip of the TPU-native WindowAggOperator hot path
(batched scatter-combine, the replacement for the reference's per-record
``WindowOperator.processElement`` → ``HeapAggregatingState`` loop) against a
single-threaded dict-based HeapStateBackend analog measured in-process (the
reference publishes no absolute numbers — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def make_batches(n_records: int, n_keys: int, batch_size: int, window_ms: int,
                 seed: int = 7):
    rng = np.random.default_rng(seed)
    batches = []
    t = 0
    for lo in range(0, n_records, batch_size):
        b = min(batch_size, n_records - lo)
        keys = rng.integers(0, n_keys, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        # event time advances ~1ms per 1k records -> several windows per run
        ts = t + np.sort(rng.integers(0, 1000, b)).astype(np.int64)
        t += 1000
        batches.append((keys, vals, ts))
    return batches


def run_tpu_native(batches, window_ms: int) -> "tuple[float, int]":
    """(records/sec, windows fired) through WindowAggOperator."""
    import jax
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    def build():
        op = WindowAggOperator(
            TumblingEventTimeWindows.of(window_ms), SumAggregator(jnp.float32),
            key_column="k", value_column="v",
            initial_key_capacity=1 << 20,
            # terminal sink: emissions may materialize one call later, so the
            # device->host download of fired windows overlaps the next
            # micro-batch's device work (tunnel is the bottleneck)
            async_fire=True)
        op.open(RuntimeContext())
        return op

    def run(op, subset):
        t0 = time.perf_counter()
        n = 0
        fired = 0
        for keys, vals, ts in subset:
            out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                               timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
            fired += sum(len(b) for b in out)
            n += len(keys)
        tail = op.end_input()
        fired += sum(len(b) for b in tail)
        if tail:
            np.asarray(tail[-1].column("result"))  # block until ready
        return n / (time.perf_counter() - t0), fired

    # warmup: cover the full key-capacity ladder so the timed run never
    # compiles — one synthetic pass inserts every key, then real batches.
    # The SAME operator instance is reused (jit caches key on the instance);
    # reset_state() drops data but keeps compiled steps.
    nk = 1 + int(max(b[0].max() for b in batches))
    bsz = len(batches[0][0])
    allkeys = np.arange(nk, dtype=np.int64)
    warm = [(allkeys[lo:lo + bsz],
             np.zeros(min(bsz, nk - lo), np.float32),
             np.zeros(min(bsz, nk - lo), np.int64))
            for lo in range(0, nk, bsz)]
    op = build()
    run(op, warm + batches[:2] + batches[-1:])
    # best of two timed passes: the tunnel transport's bandwidth swings
    # several-fold between minutes — a single pass samples the weather as
    # much as the operator.  Both passes are complete, honest runs.
    best = (0.0, 0)
    for _ in range(2):
        op.reset_state()
        rps, fired = run(op, batches)
        if rps > best[0]:
            best = (rps, fired)
    return best


def measure_fire_latency(batches, window_ms: int,
                         max_fires: int = 24) -> float:
    """p99 window-fire latency: watermark arrival -> fired rows materialized
    on the host (synchronous fires; the latency half of BASELINE.json's
    metric pair).  Uses a subset of the workload (state still reaches full
    key cardinality via the warmup batches)."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    op = WindowAggOperator(
        TumblingEventTimeWindows.of(window_ms), SumAggregator(jnp.float32),
        key_column="k", value_column="v", initial_key_capacity=1 << 20,
        async_fire=False)
    op.open(RuntimeContext())
    # warm compiles/allocations outside the timed samples: two synthetic
    # batch+fire cycles over the full key range
    rng = np.random.default_rng(3)
    warm_keys = batches[0][0]
    for i in range(2):
        wts = np.sort(rng.integers(0, window_ms, len(warm_keys))).astype(
            np.int64) + i * window_ms
        op.process_batch(RecordBatch(
            {"k": warm_keys, "v": np.ones(len(warm_keys), np.float32)},
            timestamps=wts))
        op.process_watermark(Watermark((i + 1) * window_ms - 1))
    op.reset_state()
    lats = []
    for i, (keys, vals, ts) in enumerate(batches):
        # re-time: one full window per batch, so every watermark fires
        ts = i * window_ms + np.sort(
            rng.integers(0, window_ms, len(keys))).astype(np.int64)
        op.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))
        t0 = time.perf_counter()
        out = op.process_watermark(Watermark((i + 1) * window_ms - 1))
        if out:
            np.asarray(out[-1].column("result"))  # block until on host
            lats.append(time.perf_counter() - t0)
            if len(lats) >= max_fires:
                break
    if not lats:
        return 0.0
    return float(np.percentile(np.asarray(lats) * 1000.0, 99))


def run_heap_baseline(batches, window_ms: int, budget_s: float = 30.0) -> float:
    """Single-node per-record Python dict loop — the HeapStateBackend /
    CopyOnWriteStateMap analog (reference hot loop, SURVEY §3.3(c))."""
    state = {}
    fired = 0
    t0 = time.perf_counter()
    n = 0
    for keys, vals, ts in batches:
        kl = keys.tolist()
        vl = vals.tolist()
        tl = ts.tolist()
        for k, v, t in zip(kl, vl, tl):
            w = t // window_ms
            sk = (k, w)
            acc = state.get(sk)
            state[sk] = v if acc is None else acc + v
        # watermark: fire windows whose end passed (emit + evict)
        wm = tl[-1] - 1
        done = [sk for sk in state if (sk[1] + 1) * window_ms - 1 <= wm]
        for sk in done:
            state.pop(sk)
            fired += 1
        n += len(kl)
        if time.perf_counter() - t0 > budget_s:
            break
    elapsed = time.perf_counter() - t0
    return n / elapsed, fired


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small fast run")
    ap.add_argument("--records", type=int, default=0)
    ap.add_argument("--keys", type=int, default=1_000_000)
    ap.add_argument("--batch-size", type=int, default=1 << 18)
    ap.add_argument("--window-ms", type=int, default=5000)
    args = ap.parse_args()

    n_records = args.records or (1 << 18 if args.smoke else 1 << 24)
    n_keys = min(args.keys, n_records)
    batches = make_batches(n_records, n_keys, args.batch_size, args.window_ms)

    tpu_rps, tpu_fired = run_tpu_native(batches, args.window_ms)
    # few samples on purpose: each fire is a synchronous ~4MB download and
    # the tunnel's bandwidth varies wildly — more samples would mostly
    # sample transport weather, not the operator
    p99_ms = measure_fire_latency(batches, args.window_ms,
                                  max_fires=4 if args.smoke else 8)
    # best-of-two on BOTH sides: the TPU path takes the max of two passes
    # (tunnel variance), so the baseline gets the same treatment — a
    # one-sided max would bias vs_baseline upward
    base_budget = 3.0 if args.smoke else 15.0
    base_rps = max(run_heap_baseline(batches, args.window_ms, base_budget)[0]
                   for _ in range(2))

    import jax
    platform = jax.devices()[0].platform
    print(json.dumps({
        "metric": f"records/sec/chip (1M-key tumbling sum, {platform})",
        "value": round(tpu_rps, 1),
        "unit": "records/sec",
        "p99_fire_latency_ms": round(p99_ms, 1),
        "vs_baseline": round(tpu_rps / base_rps, 3),
    }))
    print(f"# details: n={n_records} keys={n_keys} windows_fired={tpu_fired} "
          f"heap_baseline={base_rps:,.0f} rec/s  tpu_native={tpu_rps:,.0f} rec/s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
