"""North-star benchmark: 1M-key tumbling windowed sum (BASELINE.json).

Measures records/sec/chip of the TPU-native WindowAggOperator hot path —
batched scatter-combine on device state plus the write-through HOST emit
tier serving window fires (the replacement for the reference's per-record
``WindowOperator.processElement`` → ``HeapAggregatingState`` loop and its
``emitWindowContents`` fire path) — in the CHECKPOINTABLE configuration:
synchronous fires, mid-run snapshots taken inside the timed region, and a
restore+replay equivalence check after the run.

Baselines (the reference publishes no absolute numbers — BASELINE.md):
- ``heap``: single-threaded per-record Python dict loop (the driver-defined
  HeapStateBackend analog; ``vs_baseline`` is against this).
- ``numpy``: a competent vectorized single-core CPU implementation (same
  C++ key index, bincount accumulation, vectorized fires) — published so
  the device path is compared against a strong CPU contender, not only the
  interpreted loop (VERDICT r2 weak #4).

Emit-tier note (VERDICT r2 weak #1): on this environment's tunnel
transport, device->host downloads cost ~100ms fixed + ~350ms/MB while
uploads run ~1.5GB/s; any fire-time download therefore caps throughput at
~1.3M rec/s and makes sub-100ms fire latency physically impossible.  The
operator's ``emit_tier="host"`` keeps a write-through host value mirror of
the ACC cells (see ``operators/window_agg.py``) so fires and snapshots ship
zero device->host bytes; the device state stays the authoritative sharded
copy and is verified against the mirror after the run (``verify_mirror``,
a real device download).  The per-phase breakdown below makes the split
between host work, uploads, and device work explicit.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# JAX_PLATFORMS=cpu smoke-runs the bench without touching the one chip
# (the site hook would otherwise override the env var)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from flink_tpu.utils.platform import honor_jax_platforms  # noqa: E402

honor_jax_platforms()


def _early_mesh_device_flags() -> None:
    """``--mesh-devices N`` on a CPU target needs
    ``--xla_force_host_platform_device_count=N`` BEFORE the first backend
    init (argparse runs after module import, so peek at argv here) — the
    laptop/CI recipe for exercising real multi-device sharding without a
    pod (docs/operations.md "Multi-chip execution")."""
    argv = sys.argv
    n = 0
    for i, a in enumerate(argv):
        if a == "--mesh-devices" and i + 1 < len(argv):
            n = int(argv[i + 1])
        elif a.startswith("--mesh-devices="):
            n = int(a.split("=", 1)[1])
    if n > 1 and os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}")


_early_mesh_device_flags()


def _guard_wedged_accelerator(probe_timeout_s: int = 180,
                              retry_backoff_s: float = 20.0) -> None:
    """The tunnel transport can wedge PERMANENTLY (a SIGKILLed client's
    grant is never released; observed in round 5): ``jax.devices()`` then
    hangs forever in every process.  Probe the accelerator in a THROWAWAY
    subprocess first; on failure, wait out a backoff and re-probe ONCE —
    the first probe's graceful SIGTERM (plus the process-group reap of any
    orphaned jax helpers) is itself the tunnel re-initialization attempt,
    and a transiently-busy grant often frees within seconds.  Only after
    the retry fails does the bench fall back to CPU, reporting an honest
    (slower) number instead of hanging the whole round.  Skipped only when
    the caller already pinned CPU (JAX_PLATFORMS=cpu) — an accelerator
    target still probes, because the env var cannot tell a healthy tunnel
    from a wedged one.

    The probe/reap/retry machinery is the DeviceHealthMonitor's
    (``flink_tpu/runtime/device_health.py``): the production runtime's
    watchdog + background healer and this pre-flight guard share one
    recovery path."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return
    from flink_tpu.runtime.device_health import (DeviceHealthMonitor,
                                                 WatchdogConfig,
                                                 probe_backend_subprocess)
    mon = DeviceHealthMonitor(
        WatchdogConfig(probe_timeout_s=float(probe_timeout_s)),
        probe_fn=lambda: probe_backend_subprocess(probe_timeout_s),
        heal_async=False)
    if mon.probe_with_backoff(
            attempts=2, backoff_s=retry_backoff_s,
            on_retry=lambda _n, b: print(
                f"# accelerator probe failed: retrying once after "
                f"{b:.0f}s backoff (tunnel re-init)", file=sys.stderr)):
        return                               # accelerator healthy
    print("# accelerator probe failed or timed out twice: falling back to "
          "CPU (tunnel wedged?)", file=sys.stderr)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass


_guard_wedged_accelerator()


def _pick_native_shards() -> int:
    """The operator's own process-wide shard calibration (measured serial
    vs parallel on a throwaway mirror — see
    ``state/native_mirror.calibrated_shards``), surfaced here so the bench
    prints the pick before the run."""
    from flink_tpu.state.native_mirror import calibrated_shards

    pick = calibrated_shards()
    print(f"# native-shards calibration -> {pick}", file=sys.stderr)
    return pick


def make_batches(n_records: int, n_keys: int, batch_size: int, window_ms: int,
                 seed: int = 7):
    rng = np.random.default_rng(seed)
    batches = []
    t = 0
    for lo in range(0, n_records, batch_size):
        b = min(batch_size, n_records - lo)
        keys = rng.integers(0, n_keys, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        # event time advances ~1ms per 1k records -> several windows per run
        ts = t + np.sort(rng.integers(0, 1000, b)).astype(np.int64)
        t += 1000
        batches.append((keys, vals, ts))
    return batches


def _build_op(window_ms: int, emit_tier: str = "host",
              device_sync: str = "auto", paging_cap: int = 0,
              pipeline_depth: int = 1, native_shards: int = 0,
              mesh_devices: int = 0, key_capacity: int = 1 << 20,
              device_probe: str = "auto", queryable=None,
              superbatch: int = 0):
    import jax.numpy as jnp

    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    paging = None
    if paging_cap:
        from flink_tpu.state.paging import PagingConfig
        paging = PagingConfig(capacity=paging_cap)
        emit_tier = "device"   # paging pins the device tier
    kw = dict(
        key_column="k", value_column="v",
        initial_key_capacity=key_capacity,
        emit_tier=emit_tier,
        snapshot_source="mirror" if emit_tier == "host" else "device",
        device_sync=device_sync if emit_tier == "host" else "scatter",
        paging=paging,
        # the bench IS the hot-path deployment: pipelined by default
        # (--pipeline-depth 0 A/Bs the serial path), native probe sharded
        # across cores (--native-shards; 0 = auto), device-resident key
        # probe behind --device-probe (auto = measured A/B calibration)
        pipeline_depth=pipeline_depth,
        native_shards=native_shards,
        device_probe=device_probe,
        queryable=queryable,
        # one-dispatch fused megastep (ISSUE-11): stage N micro-batches
        # and advance them in one pass (0 = measured auto-calibration)
        superbatch=superbatch)
    if mesh_devices > 1:
        # the mesh-sharded hot path: ONE logical operator over the chip
        # mesh (parallel/mesh_runtime) — state in key-group-range blocks,
        # records routed by on-device all_to_all, probe sharded per device
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator
        op = MeshWindowAggOperator(
            TumblingEventTimeWindows.of(window_ms),
            SumAggregator(jnp.float32), mesh=make_mesh(mesh_devices), **kw)
    else:
        op = WindowAggOperator(
            TumblingEventTimeWindows.of(window_ms),
            SumAggregator(jnp.float32), **kw)
    op.open(RuntimeContext())
    return op


def run_paged(batches, window_ms: int, checkpoint_every: int, cap: int,
              pipeline_depth: int = 1, native_shards: int = 0):
    """One full paged pass (device tier, K_cap = ``cap``): the cold-key
    paging subsystem's cost + occupancy on the headline workload.  Returns
    (records/sec, paging stats, phase dict)."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    op = _build_op(window_ms, paging_cap=cap, pipeline_depth=pipeline_depth,
                   native_shards=native_shards)
    t0 = time.perf_counter()
    n = 0
    for i, (keys, vals, ts) in enumerate(batches):
        out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                           timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        n += len(keys)
        if checkpoint_every and (i + 1) % checkpoint_every == 0:
            op.prepare_snapshot_pre_barrier()
            op.snapshot_state()
    stats = dict(op.paging_stats())   # occupancy BEFORE end-of-input drains
    tail = op.end_input()
    if tail:
        np.asarray(tail[-1].column("result"))
    elapsed = time.perf_counter() - t0
    stats["evictions"] = op.paging_stats()["evictions"]
    stats["promotions"] = op.paging_stats()["promotions"]
    return n / elapsed, stats, dict(op.phase_ns)


def _fire_digests(elements):
    """(window_start, rows, sum(result)) per fired batch — the equivalence
    fingerprint for restore+replay checks."""
    out = []
    for b in elements:
        if hasattr(b, "columns") and "result" in b.columns:
            out.append((int(np.asarray(b.column("window_start"))[0]),
                        len(b),
                        float(np.asarray(b.column("result"),
                                         np.float64).sum())))
    return out


def run_tpu_native(batches, window_ms: int, checkpoint_every: int,
                   emit_tier: str = "host", device_sync: str = "auto",
                   timed_passes: int = 3, pipeline_depth: int = 1,
                   native_shards: int = 0, mesh_devices: int = 0,
                   key_capacity: int = 1 << 20, device_probe: str = "auto",
                   superbatch: int = 0):
    """Timed checkpointable run.  Returns (records/sec, windows fired,
    snapshots taken, phase dict, mid-run snapshot + its batch index +
    post-checkpoint digests for the replay check)."""
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.observability import tracing

    def run(op, subset, checkpoint_every=0):
        t0 = time.perf_counter()
        n = 0
        fired = 0
        snaps = 0
        mid = None
        digests = []
        snap_ns = 0
        for i, (keys, vals, ts) in enumerate(subset):
            out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                               timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
            fired += sum(len(b) for b in out)
            if mid is not None:
                digests.extend(_fire_digests(out))
            n += len(keys)
            if checkpoint_every and (i + 1) % checkpoint_every == 0:
                # checkpoint lifecycle spans (no-ops unless a span journal
                # is installed — the --trace leg): trigger → snapshot →
                # complete on the same timeline as the hot-stage phases
                cid = snaps + 1
                tracing.instant("checkpoint.trigger", cat="checkpoint",
                                checkpoint=cid)
                s0 = time.perf_counter_ns()
                with tracing.span("checkpoint.snapshot", cat="checkpoint",
                                  checkpoint=cid):
                    op.prepare_snapshot_pre_barrier()
                    snap = op.snapshot_state()
                s1 = time.perf_counter_ns()
                tracing.complete("checkpoint", s0, s1, cat="checkpoint",
                                 checkpoint=cid)
                snap_ns += s1 - s0
                snaps += 1
                if mid is None:          # keep the FIRST mid-run snapshot
                    mid = (i, snap)
        tail = op.end_input()
        fired += sum(len(b) for b in tail)
        if mid is not None:
            digests.extend(_fire_digests(tail))
        if tail:
            np.asarray(tail[-1].column("result"))  # block until ready
        elapsed = time.perf_counter() - t0
        # capture THIS pass's phase accounting (reset_state clears it), so
        # the reported breakdown always belongs to the winning pass
        phases = dict(op.phase_ns)
        phases["snapshot_total"] = snap_ns
        phases["elapsed"] = int(elapsed * 1e9)
        shard_ns = {k: [int(x) for x in v.tolist()]
                    for k, v in op.phase_shard_ns.items()}
        return (n / elapsed, fired, snaps, mid, digests,
                phases, dict(op.phase_bytes), shard_ns)

    # warmup: cover the full key-capacity ladder so the timed run never
    # compiles — one synthetic pass inserts every key, then real batches.
    # The SAME operator instance is reused (jit caches key on the instance);
    # reset_state() drops data but keeps compiled steps.
    nk = 1 + int(max(b[0].max() for b in batches))
    bsz = len(batches[0][0])
    allkeys = np.arange(nk, dtype=np.int64)
    warm = [(allkeys[lo:lo + bsz],
             np.zeros(min(bsz, nk - lo), np.float32),
             np.zeros(min(bsz, nk - lo), np.int64))
            for lo in range(0, nk, bsz)]
    op = _build_op(window_ms, emit_tier, device_sync,
                   pipeline_depth=pipeline_depth, native_shards=native_shards,
                   mesh_devices=mesh_devices, key_capacity=key_capacity,
                   device_probe=device_probe, superbatch=superbatch)
    run(op, warm + batches[:2] + batches[-1:])
    # best of three timed passes: this host suffers EPISODIC multi-second
    # slowdowns (shared-core tunnel client; measured ±70% swings on
    # otherwise-stable C kernels) — every pass is a complete, honest run
    # with the SAME checkpoint cadence, and the baselines get the same
    # best-of treatment below.  GC is paused inside the timed region
    # (bench hygiene; re-enabled after).
    import gc
    best = None
    for _ in range(timed_passes):
        op.reset_state()
        gc.disable()
        try:
            res = run(op, batches, checkpoint_every)
        finally:
            gc.enable()
        if best is None or res[0] > best[0]:
            best = res
    rps, fired, snaps, mid, digests, phases, bytes_, shard_ns = best
    return (rps, fired, snaps, mid, digests, phases, bytes_, shard_ns, op)


def replay_check(batches, window_ms: int, mid, digests,
                 emit_tier: str = "host", device_sync: str = "auto",
                 pipeline_depth: int = 1, native_shards: int = 0,
                 mesh_devices: int = 0, key_capacity: int = 1 << 20,
                 device_probe: str = "auto", superbatch: int = 0) -> bool:
    """Exactly-once evidence: restore the mid-run snapshot into a FRESH
    operator, replay the remaining batches, and require the identical
    per-window fire digests."""
    if mid is None:
        return True
    from flink_tpu.core.batch import RecordBatch, Watermark

    i, snap = mid
    op = _build_op(window_ms, emit_tier, device_sync,
                   pipeline_depth=pipeline_depth, native_shards=native_shards,
                   mesh_devices=mesh_devices, key_capacity=key_capacity,
                   device_probe=device_probe, superbatch=superbatch)
    op.restore_state(snap)
    out = []
    for keys, vals, ts in batches[i + 1:]:
        out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                            timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
    out += op.end_input()
    got = _fire_digests(out)
    if len(got) != len(digests):
        return False
    for (w1, n1, s1), (w2, n2, s2) in zip(got, digests):
        if w1 != w2 or n1 != n2 or abs(s1 - s2) > 1e-6 * max(abs(s2), 1.0):
            return False
    return True


def measure_fire_latency(batches, window_ms: int,
                         min_samples: int = 128,
                         max_samples: int = 256,
                         emit_tier: str = "host",
                         device_sync: str = "auto",
                         pipeline_depth: int = 1,
                         native_shards: int = 0,
                         device_probe: str = "auto") -> dict:
    """Window-fire latency: watermark arrival -> fired rows materialized on
    the host.  >= ``min_samples`` samples (VERDICT r2 weak #2), capped at
    ``max_samples`` (each device-tier sample is a real synchronous
    download); each cycle fills one full window then fires it.  Returns
    p50/p95/p99 ms."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    rng = np.random.default_rng(3)
    # split batches into half-batches until there are enough fire cycles
    cycles = list(batches)
    while len(cycles) < min_samples:
        halved = []
        for keys, vals, ts in cycles:
            h = len(keys) // 2
            if h == 0:
                halved.append((keys, vals, ts))
                continue
            halved.append((keys[:h], vals[:h], ts[:h]))
            halved.append((keys[h:], vals[h:], ts[h:]))
        if len(halved) == len(cycles):
            break
        cycles = halved
    cycles = cycles[:max_samples]
    op = _build_op(window_ms, emit_tier, device_sync,
                   pipeline_depth=pipeline_depth, native_shards=native_shards,
                   device_probe=device_probe)
    # warm compiles/allocations outside the timed samples
    warm_keys = batches[0][0]
    for i in range(2):
        wts = np.sort(rng.integers(0, window_ms, len(warm_keys))).astype(
            np.int64) + i * window_ms
        op.process_batch(RecordBatch(
            {"k": warm_keys, "v": np.ones(len(warm_keys), np.float32)},
            timestamps=wts))
        op.process_watermark(Watermark((i + 1) * window_ms - 1))
    op.reset_state()
    lats = []
    for i, (keys, vals, _ts) in enumerate(cycles):
        # re-time: one full window per cycle, so every watermark fires
        ts = i * window_ms + np.sort(
            rng.integers(0, window_ms, len(keys))).astype(np.int64)
        op.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))
        t0 = time.perf_counter()
        out = op.process_watermark(Watermark((i + 1) * window_ms - 1))
        if out:
            np.asarray(out[-1].column("result"))  # block until on host
            lats.append(time.perf_counter() - t0)
    if not lats:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0, "samples": 0}
    ms = np.asarray(lats) * 1000.0
    return {"p50": float(np.percentile(ms, 50)),
            "p95": float(np.percentile(ms, 95)),
            "p99": float(np.percentile(ms, 99)),
            "samples": int(ms.size)}


def _gc_paused(fn):
    """Same GC treatment as the TPU timed passes (methodology symmetry)."""
    import functools
    import gc

    @functools.wraps(fn)
    def wrapped(*a, **kw):
        gc.disable()
        try:
            return fn(*a, **kw)
        finally:
            gc.enable()
    return wrapped


@_gc_paused
def run_heap_baseline(batches, window_ms: int, budget_s: float = 30.0):
    """Single-node per-record Python dict loop — the HeapStateBackend /
    CopyOnWriteStateMap analog (reference hot loop, SURVEY §3.3(c))."""
    state = {}
    fired = 0
    t0 = time.perf_counter()
    n = 0
    for keys, vals, ts in batches:
        kl = keys.tolist()
        vl = vals.tolist()
        tl = ts.tolist()
        for k, v, t in zip(kl, vl, tl):
            w = t // window_ms
            sk = (k, w)
            acc = state.get(sk)
            state[sk] = v if acc is None else acc + v
        # watermark: fire windows whose end passed (emit + evict)
        wm = tl[-1] - 1
        done = [sk for sk in state if (sk[1] + 1) * window_ms - 1 <= wm]
        for sk in done:
            state.pop(sk)
            fired += 1
        n += len(kl)
        if time.perf_counter() - t0 > budget_s:
            break
    elapsed = time.perf_counter() - t0
    return n / elapsed, fired


@_gc_paused
def run_numpy_baseline(batches, window_ms: int):
    """Competent vectorized CPU contender: C++ hash key index (fair — the
    reference's heap backend is compiled Java), one bincount per
    (batch, pane), vectorized fires.  Single core."""
    from flink_tpu.state.keyindex import make_key_index

    index = None
    panes: dict = {}          # pane -> float64[cap] sums
    counts: dict = {}         # pane -> int64[cap]
    cap = 1 << 20
    fired = 0
    t0 = time.perf_counter()
    n = 0
    for keys, vals, ts in batches:
        if index is None:
            index = make_key_index(keys[0])
        slots = index.lookup_or_insert(keys)
        while index.num_keys > cap:
            cap <<= 1
        pane = ts // window_ms
        for p in np.unique(pane).tolist():
            m = pane == p
            s = slots[m] if not m.all() else slots
            v = vals[m] if not m.all() else vals
            arr = panes.get(p)
            if arr is None or arr.size < cap:
                grown = np.zeros(cap, np.float64)
                cnt = np.zeros(cap, np.int64)
                if arr is not None:
                    grown[:arr.size] = arr
                    cnt[:arr.size] = counts[p]
                panes[p], counts[p] = arr, cnt = grown, cnt
            panes[p] += np.bincount(s, weights=v, minlength=cap)
            counts[p] += np.bincount(s, minlength=cap)
        # fire windows whose end passed
        wm = int(ts.max()) - 1
        done = [p for p in panes if (p + 1) * window_ms - 1 <= wm]
        for p in sorted(done):
            nz = np.flatnonzero(counts[p][:index.num_keys] > 0)
            if nz.size:
                _result = panes[p][nz]              # emitted values
                _keys = np.asarray(index.reverse_keys())[nz]
                fired += nz.size
            del panes[p], counts[p]
        n += len(keys)
    # end of input: flush
    for p in sorted(panes):
        nz = np.flatnonzero(counts[p][:index.num_keys] > 0)
        fired += int(nz.size)
    elapsed = time.perf_counter() - t0
    return n / elapsed, fired


# ---------------------------------------------------------------------------
# BASELINE.md configs 1/3/4/5 (config 2 — the 1M-key tumbling sum — is the
# headline path below; these run via --config N)
# ---------------------------------------------------------------------------


def _best_of(fn, passes: int):
    """Best-of-N timed passes with GC paused (same methodology as the
    headline run; this host shows episodic multi-second slowdowns)."""
    import gc
    best = None
    for _ in range(passes):
        gc.disable()
        try:
            res = fn()
        finally:
            gc.enable()
        if best is None or res[0] > best[0]:
            best = res
    return best


def _drain(op, batches, key_col="k"):
    """Feed (cols, ts) batches through an operator with per-batch
    watermarks; returns (records, fired rows, elapsed_s)."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    t0 = time.perf_counter()
    n = 0
    fired = 0
    for cols, ts in batches:
        out = op.process_batch(RecordBatch(cols, timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        fired += sum(len(b) for b in out if hasattr(b, "columns"))
        n += len(ts)
    tail = op.end_input()
    fired += sum(len(b) for b in tail if hasattr(b, "columns"))
    if tail and hasattr(tail[-1], "columns"):
        cols = tail[-1].columns
        np.asarray(next(iter(cols.values())))   # block until on host
    return n, fired, time.perf_counter() - t0


def _result(cfg: int, metric: str, rps: float, heap_rps: float,
            extra: dict) -> dict:
    return {
        "metric": metric,
        "value": round(rps, 1),
        "unit": "records/sec",
        "config": cfg,
        "vs_baseline": round(rps / heap_rps, 3),
        "details": {"heap_baseline_rps": round(heap_rps, 1), **extra},
    }


# ---- config 1: socket-style WordCount (Tumbling 5s count per word) --------

def _make_lines(n_words: int, vocab: int, seed: int = 11):
    """Text lines (10 words each), Zipf word frequencies — the
    SocketWindowWordCount input shape.  Returns [(lines, ts_ms)]."""
    rng = np.random.default_rng(seed)
    words = np.asarray([f"w{i:05d}" for i in range(vocab)], object)
    ranks = rng.zipf(1.3, n_words).astype(np.int64) % vocab
    flat = words[ranks]
    per_line = 10
    lines = [" ".join(flat[i:i + per_line])
             for i in range(0, n_words, per_line)]
    batches = []
    bsz = 4096                       # lines per batch (~41k words)
    t = 0
    for lo in range(0, len(lines), bsz):
        chunk = lines[lo:lo + bsz]
        ts = t + np.sort(rng.integers(0, 1000, len(chunk))).astype(np.int64)
        t += 1000
        batches.append((chunk, ts))
    return batches


def run_config1(smoke: bool) -> dict:
    """WordCount: tokenize lines (the flatMap), keyBy(word),
    Tumbling(5s) count — ``SocketWindowWordCount.java:69-84``.  The socket
    is not benchmarked (that would measure the kernel's TCP stack);
    tokenization IS in the timed region on both sides."""
    import jax.numpy as jnp
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    n_words = 1 << 17 if smoke else 1 << 22
    batches = _make_lines(n_words, vocab=30_000)

    def tokenize(chunk, ts):
        words, wts = [], []
        for line, t in zip(chunk, ts.tolist()):
            ws = line.split()
            words.extend(ws)
            wts.extend([t] * len(ws))
        return (np.asarray(words, object),
                np.ones(len(words), np.float32),
                np.asarray(wts, np.int64))

    def mk_op():
        op = WindowAggOperator(
            TumblingEventTimeWindows.of(5000), SumAggregator(jnp.float32),
            key_column="k", value_column="v", emit_tier="host",
            snapshot_source="mirror", device_sync="auto")
        op.open(RuntimeContext())
        return op

    op = mk_op()
    for chunk, ts in batches[:2]:            # warm compiles
        k, v, wts = tokenize(chunk, ts)
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=wts))
    op.reset_state()

    def tpu_pass():
        op.reset_state()
        t0 = time.perf_counter()
        n = fired = 0
        for chunk, ts in batches:
            k, v, wts = tokenize(chunk, ts)
            out = op.process_batch(RecordBatch({"k": k, "v": v},
                                               timestamps=wts))
            out += op.process_watermark(Watermark(int(wts[-1]) - 1))
            fired += sum(len(b) for b in out if hasattr(b, "columns"))
            n += len(k)
        tail = op.end_input()
        fired += sum(len(b) for b in tail if hasattr(b, "columns"))
        return n / (time.perf_counter() - t0), fired

    rps, fired = _best_of(tpu_pass, 2 if smoke else 3)

    def heap_pass():
        state = {}
        t0 = time.perf_counter()
        n = fired = 0
        for chunk, ts in batches:
            tl = ts.tolist()
            for line, t in zip(chunk, tl):
                for w in line.split():
                    sk = (w, t // 5000)
                    state[sk] = state.get(sk, 0) + 1
                    n += 1
            wm = tl[-1] - 1
            done = [sk for sk in state if (sk[1] + 1) * 5000 - 1 <= wm]
            for sk in done:
                state.pop(sk)
                fired += 1
            if time.perf_counter() - t0 > (3.0 if smoke else 20.0):
                break
        return n / (time.perf_counter() - t0), fired

    heap_rps, _hf = _best_of(heap_pass, 2)
    return _result(
        1, "records/sec/chip (WordCount words, Tumbling 5s count)",
        rps, heap_rps, {"windows_fired": fired, "n_words": n_words,
                        "tokenize_in_timed_region": True})


# ---- config 3: Sliding(60s, 5s) multi-field aggregate ---------------------

def run_config3(smoke: bool) -> dict:
    """Sliding(60s,5s) multi-field AggregateFunction (sum/count/min/max →
    avg): the pane-combine shape of ``HeapWindowsGrouping.java``; the heap
    baseline is the reference ``WindowOperator`` per-record behavior — each
    element updates all 12 covering windows."""
    import jax.numpy as jnp
    from flink_tpu.core.functions import (CountAggregator, MaxAggregator,
                                          MinAggregator, RuntimeContext,
                                          SumAggregator, TupleAggregator)
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows

    n = 1 << 17 if smoke else 1 << 23
    n_keys = 100_000
    rng = np.random.default_rng(13)
    batches = []
    t = 0
    bsz = 1 << 17
    for lo in range(0, n, bsz):
        b = min(bsz, n - lo)
        keys = rng.integers(0, n_keys, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        ts = t + np.sort(rng.integers(0, 5000, b)).astype(np.int64)
        t += 5000
        batches.append(({"k": keys, "v": vals}, ts))

    def mk_agg():
        return TupleAggregator({
            "total": ("v", SumAggregator(jnp.float32)),
            "n": ("v", CountAggregator()),
            "lo": ("v", MinAggregator(jnp.float32)),
            "hi": ("v", MaxAggregator(jnp.float32)),
        })

    op = WindowAggOperator(
        SlidingEventTimeWindows.of(60_000, 5_000), mk_agg(),
        key_column="k", value_selector=lambda c: c,
        emit_tier="host", snapshot_source="mirror", device_sync="auto")
    op.open(RuntimeContext())
    _drain(op, batches[:2])                  # warm compiles

    def tpu_pass():
        op.reset_state()
        nn, fired, el = _drain(op, batches)
        return nn / el, fired

    rps, fired = _best_of(tpu_pass, 2 if smoke else 3)

    def heap_pass():
        state = {}
        t0 = time.perf_counter()
        nn = fired = 0
        for cols, ts in batches:
            kl = cols["k"].tolist()
            vl = cols["v"].tolist()
            tl = ts.tolist()
            for k, v, tt in zip(kl, vl, tl):
                # every element joins the 12 sliding windows covering it
                last = tt // 5000
                for w in range(max(0, last - 11), last + 1):
                    sk = (k, w)
                    acc = state.get(sk)
                    if acc is None:
                        state[sk] = [v, 1, v, v]
                    else:
                        acc[0] += v
                        acc[1] += 1
                        if v < acc[2]:
                            acc[2] = v
                        if v > acc[3]:
                            acc[3] = v
                nn += 1
            wm = tl[-1] - 1
            done = [sk for sk in state
                    if sk[1] * 5000 + 60_000 - 1 <= wm]
            for sk in done:
                state.pop(sk)
                fired += 1
            if time.perf_counter() - t0 > (3.0 if smoke else 20.0):
                break
        return nn / (time.perf_counter() - t0), fired

    heap_rps, _hf = _best_of(heap_pass, 2)
    return _result(
        3, "records/sec/chip (Sliding 60s/5s multi-field sum/count/min/max)",
        rps, heap_rps, {"windows_fired": fired, "n_records": n,
                        "n_keys": n_keys})


# ---- config 4: session windows + Zipf keys --------------------------------

def run_config4(smoke: bool) -> dict:
    """Session windows (gap merge) under Zipf key skew —
    ``MergingWindowSet.java`` / ``WindowOperator.java:311-411``."""
    import jax.numpy as jnp
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    n = 1 << 16 if smoke else 1 << 21
    n_keys = 100_000
    gap = 1000
    rng = np.random.default_rng(17)
    batches = []
    t = 0
    bsz = 1 << 15
    for lo in range(0, n, bsz):
        b = min(bsz, n - lo)
        keys = (rng.zipf(1.3, b).astype(np.int64) - 1) % n_keys
        vals = rng.random(b).astype(np.float32)
        # bursts with inter-burst silence > gap, so sessions CLOSE
        ts = t + np.sort(rng.integers(0, 800, b)).astype(np.int64)
        t += 3000
        batches.append(({"k": keys, "v": vals}, ts))

    def mk_op():
        op = SessionWindowOperator(
            EventTimeSessionWindows(gap), SumAggregator(jnp.float32),
            key_column="k", value_column="v")
        op.open(RuntimeContext())
        return op

    op = mk_op()
    _drain(op, batches[:2])                  # warm compiles

    def tpu_pass():
        o = mk_op()                          # session op: fresh state
        nn, fired, el = _drain(o, batches)
        return nn / el, fired

    rps, fired = _best_of(tpu_pass, 2 if smoke else 3)

    def heap_pass():
        # MergingWindowSet analog: per key a list of (start, end, acc)
        sessions: dict = {}
        t0 = time.perf_counter()
        nn = fired = 0
        for cols, ts in batches:
            kl = cols["k"].tolist()
            vl = cols["v"].tolist()
            tl = ts.tolist()
            for k, v, tt in zip(kl, vl, tl):
                lst = sessions.setdefault(k, [])
                new = [tt, tt + gap, v]
                merged = []
                for s in lst:
                    if s[0] <= new[1] and new[0] <= s[1]:  # overlap: merge
                        new = [min(s[0], new[0]), max(s[1], new[1]),
                               s[2] + new[2]]
                    else:
                        merged.append(s)
                merged.append(new)
                sessions[k] = merged
                nn += 1
            wm = tl[-1] - 1
            for k in list(sessions):
                keep = []
                for s in sessions[k]:
                    if s[1] - 1 <= wm:
                        fired += 1
                    else:
                        keep.append(s)
                if keep:
                    sessions[k] = keep
                else:
                    del sessions[k]
            if time.perf_counter() - t0 > (3.0 if smoke else 20.0):
                break
        return nn / (time.perf_counter() - t0), fired

    heap_rps, _hf = _best_of(heap_pass, 2)
    return _result(
        4, "records/sec/chip (session windows gap=1s, Zipf keys)",
        rps, heap_rps, {"sessions_fired": fired, "n_records": n,
                        "gap_ms": gap})


# ---- config 5: SQL TUMBLE/HOP over a lineitem stream ----------------------

def _lineitem(n: int, seed: int = 19):
    rng = np.random.default_rng(seed)
    flags = np.asarray(["A", "N", "R"], object)
    return {
        "l_returnflag": flags[rng.integers(0, 3, n)],
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": (rng.random(n) * 1000).astype(np.float64),
        "l_discount": (rng.random(n) * 0.1).astype(np.float64),
        "ts": np.sort(rng.integers(0, 120_000, n)).astype(np.int64),
    }


def run_config5(smoke: bool) -> dict:
    """SQL TUMBLE and HOP GroupWindowAggregate over a TPC-H-like lineitem
    stream — ``StreamExecGroupWindowAggregate.java:103``.  Timed region =
    plan + execute + collect (the whole executeSql path)."""
    from flink_tpu.sql.table_env import TableEnvironment

    n = 1 << 16 if smoke else 1 << 22
    cols = _lineitem(n)
    tumble_sql = (
        "SELECT l_returnflag, "
        "TUMBLE_START(ts, INTERVAL '5' SECOND) AS ws, "
        "SUM(l_extendedprice * (1 - l_discount)) AS revenue, "
        "SUM(l_quantity) AS qty, COUNT(*) AS n FROM lineitem "
        "GROUP BY l_returnflag, TUMBLE(ts, INTERVAL '5' SECOND)")
    hop_sql = (
        "SELECT l_returnflag, "
        "HOP_START(ts, INTERVAL '5' SECOND, INTERVAL '60' SECOND) AS ws, "
        "SUM(l_extendedprice * (1 - l_discount)) AS revenue, "
        "COUNT(*) AS n FROM lineitem "
        "GROUP BY l_returnflag, "
        "HOP(ts, INTERVAL '5' SECOND, INTERVAL '60' SECOND)")

    def sql_pass(sql):
        def run():
            tenv = TableEnvironment()
            tenv.register_collection("lineitem", columns=cols,
                                     rowtime="ts", batch_size=1 << 17)
            t0 = time.perf_counter()
            rows = tenv.execute_sql(sql).collect()
            return n / (time.perf_counter() - t0), len(rows)
        return run

    warm = sql_pass(tumble_sql)()            # warm compiles
    t_rps, t_rows = _best_of(sql_pass(tumble_sql), 2 if smoke else 3)
    h_rps, h_rows = _best_of(sql_pass(hop_sql), 1 if smoke else 2)

    def heap_pass():
        state: dict = {}
        t0 = time.perf_counter()
        fl = cols["l_returnflag"].tolist()
        qty = cols["l_quantity"].tolist()
        price = cols["l_extendedprice"].tolist()
        disc = cols["l_discount"].tolist()
        tl = cols["ts"].tolist()
        nn = 0
        for f, q, p, d, tt in zip(fl, qty, price, disc, tl):
            sk = (f, tt // 5000)
            acc = state.get(sk)
            rev = p * (1 - d)
            if acc is None:
                state[sk] = [rev, q, 1]
            else:
                acc[0] += rev
                acc[1] += q
                acc[2] += 1
            nn += 1
            if nn % 65536 == 0 and \
                    time.perf_counter() - t0 > (3.0 if smoke else 20.0):
                break
        return nn / (time.perf_counter() - t0), len(state)

    heap_rps, _groups = _best_of(heap_pass, 2)
    return _result(
        5, "records/sec/chip (SQL TUMBLE 5s lineitem revenue aggregate)",
        t_rps, heap_rps,
        {"tumble_result_rows": t_rows, "hop_rps": round(h_rps, 1),
         "hop_result_rows": h_rows, "n_records": n,
         "warmup_rps": round(warm[0], 1)})


CONFIG_RUNNERS = {1: run_config1, 3: run_config3, 4: run_config4,
                  5: run_config5}


def run_wedge_smoke(window_ms: int = 1000) -> dict:
    """``--inject-wedge``: exercise the SHARED runtime/bench recovery path
    end-to-end on CPU-sized traffic.  A deterministic ``WedgedDevice``
    chaos schedule hangs the Nth hot-path dispatch; the watchdog must
    quarantine, the operator must degrade to the host tier mid-stream
    without dropping records, a snapshot must complete DURING quarantine,
    the healer must heal once the schedule does, and the operator must
    re-promote at the next checkpoint-aligned safe point — with fire
    digests identical to an unfaulted pass."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.testing import chaos
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    def build():
        op = WindowAggOperator(
            TumblingEventTimeWindows.of(window_ms),
            SumAggregator(jnp.float32), key_column="k", value_column="v",
            emit_tier="device")
        op.open(RuntimeContext())
        return op

    rng = np.random.default_rng(7)
    batches = []
    for i in range(24):
        k = rng.integers(0, 64, 512)
        v = np.ones(512, np.float32)
        ts = i * (window_ms // 2) + np.sort(
            rng.integers(0, window_ms // 2, 512)).astype(np.int64)
        batches.append((k, v, ts))

    def digests(els):
        out = []
        for b in els:
            if hasattr(b, "columns") and "result" in b.columns:
                out.append((int(np.asarray(b.column("window_start"))[0]),
                            len(b),
                            float(np.asarray(b.column("result"),
                                             np.float64).sum())))
        return out

    def one_pass(inject: bool):
        prev = dh.get_monitor(create=False)
        dh.set_monitor(dh.DeviceHealthMonitor(
            dh.WatchdogConfig(deadline_floor_s=0.5), heal_async=False))
        inj = chaos.FaultInjector(seed=3)
        sched = (inj.inject("device.dispatch", chaos.WedgedDevice(at=8))
                 if inject else None)
        op = build()
        out = []
        snapshotted_degraded = False
        try:
            with chaos.installed(inj):
                for i, (k, v, ts) in enumerate(batches):
                    out += op.process_batch(
                        RecordBatch({"k": k, "v": v}, timestamps=ts))
                    out += op.process_watermark(Watermark(int(ts.max()) - 1))
                    if inject and i == 12:
                        op.prepare_snapshot_pre_barrier()
                        op.snapshot_state()   # checkpoint DURING quarantine
                        snapshotted_degraded = op._degraded
                        sched.heal()
                        dh.get_monitor().probe_now()
                    if inject and i == 16:
                        out += op.prepare_snapshot_pre_barrier()  # repromote
                out += op.end_input()
            stats = op.device_health_stats()
            mon = dh.get_monitor().status()
            op.close()
        finally:
            dh.set_monitor(prev)
        return digests(out), stats, mon, snapshotted_degraded

    clean, _s, _m, _d = one_pass(False)
    wedged, stats, mon, snap_degraded = one_pass(True)
    ok = (clean == wedged and mon["quarantines"] == 1 and mon["heals"] == 1
          and stats["quarantine_migrations"] == 1
          and stats["repromotions"] == 1 and stats["degraded"] == 0
          and snap_degraded)
    return {"metric": "inject-wedge recovery smoke", "ok": ok,
            "digest_match": clean == wedged,
            "snapshot_during_quarantine": snap_degraded,
            "device_health": {**{k: mon[k] for k in
                                 ("state", "quarantines", "heals",
                                  "watchdog_timeouts")}, **stats}}


def run_checkpoint_backpressure(interval_ms: int, budget_ms: float,
                                min_completed: int = 1,
                                n_records: int = 40_000) -> dict:
    """``--checkpoint-interval``: checkpoint duration + persisted in-flight
    bytes under INJECTED backpressure (ISSUE-5 CI satellite).  A seeded
    ``SlowConsumer`` schedule stalls one source's channels into the keyed
    window subtasks (bursty drain stalls — input queues deepen, barriers
    crawl behind the backlog) while a ``SlowDisk`` schedule stalls the
    checkpoint store; the job runs with aligned-with-timeout escalation,
    so checkpoints must keep completing within ``budget_ms`` regardless —
    the unaligned-checkpoint acceptance in bench form."""
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
    from flink_tpu.testing import chaos
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    rng = np.random.default_rng(11)
    keys = rng.integers(0, 101, n_records)
    vals = np.ones(n_records, np.float64)
    ts = np.sort(rng.integers(0, 4000, n_records))
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=256)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v").collect())
    inj = chaos.FaultInjector(seed=29)
    inj.inject("channel.recv",
               chaos.SlowConsumer(max_s=0.03, min_s=0.015, p=0.3, burst=30,
                                  channel="[0]->"))
    inj.inject("checkpoint.store",
               chaos.SlowDisk(max_s=0.04, min_s=0.01, p=0.5, times=30))
    storage = InMemoryCheckpointStorage(retain=5)
    t0 = time.monotonic()
    with chaos.installed(inj):
        res = env.execute_cluster(
            storage=storage, checkpoint_interval_ms=interval_ms,
            checkpoint_timeout_s=max(2.0, budget_ms / 1000.0),
            alignment_timeout_ms=100, tolerable_failed_checkpoints=-1,
            timeout_s=300)
    wall_ms = (time.monotonic() - t0) * 1000.0
    status = env._last_cluster.job_status()
    stats = status["checkpoint_stats"]
    durations = [s["duration_ms"] for s in stats]
    persisted = [s["persisted_inflight_bytes"] for s in stats]
    completed = len(res.completed_checkpoints)
    unaligned = sum(1 for s in stats if s["unaligned"])
    rows = sum(float(r["v"]) for r in sink.rows())
    exactly_once = abs(rows - float(vals.sum())) < 0.5
    ok = (res.state == "FINISHED" and completed >= min_completed
          and exactly_once and durations
          and max(durations) <= budget_ms)
    return {
        "metric": "checkpoint duration under injected backpressure",
        "ok": ok,
        "state": res.state,
        "exactly_once": exactly_once,
        "completed_checkpoints": completed,
        "unaligned_checkpoints": unaligned,
        "failed_checkpoints": status["checkpoints"]["failed_checkpoints"],
        "checkpoint_interval_ms": interval_ms,
        "budget_ms": budget_ms,
        "max_duration_ms": max(durations) if durations else None,
        "mean_duration_ms": (round(sum(durations) / len(durations), 1)
                             if durations else None),
        "max_alignment_ms": max((s["alignment_ms"] for s in stats),
                                default=0.0),
        "persisted_inflight_bytes_total": int(sum(persisted)),
        "persisted_inflight_bytes_max": int(max(persisted, default=0)),
        "overtaken_bytes_total": int(sum(s["overtaken_bytes"]
                                         for s in stats)),
        "wall_ms": round(wall_ms, 1),
    }


def _tree_eq(a, b) -> bool:
    """Bit-exact structural equality of two snapshot trees (bool form of
    the test suite's assertion helper — the bench must report, not raise)."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        return (a.dtype == b.dtype and a.shape == b.shape
                and bool(np.array_equal(a, b)))
    if isinstance(a, dict):
        return (isinstance(b, dict) and a.keys() == b.keys()
                and all(_tree_eq(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)):
        return (isinstance(b, (list, tuple)) and len(a) == len(b)
                and all(_tree_eq(x, y) for x, y in zip(a, b)))
    return bool(a == b)


def run_incremental_checkpoint_bench(smoke: bool = False,
                                     churn_frac: float = 0.10,
                                     rounds: int = 5) -> dict:
    """``--checkpoint-interval`` incremental leg (ISSUE-16): at a steady
    state where ``churn_frac`` of the keys change per interval, measure
    bytes/checkpoint for delta cuts vs the full dense snapshot, the
    increments-per-base chain depth in ``IncrementalCheckpointStorage``,
    and the measured recovery time (chain resolve + operator restore).
    The chain-restored state must be digest-identical to the full
    snapshot — reported as ``digest_match`` and gated unconditionally by
    ``check_incremental_budget``."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.base import snapshot_scope
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.runtime.checkpoint import delta
    from flink_tpu.runtime.checkpoint.incremental import \
        IncrementalCheckpointStorage
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    n_keys = 50_000 if smoke else 1_000_000
    churn = max(1, int(n_keys * churn_frac))
    rng = np.random.default_rng(17)
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(jnp.float32),
                           key_column="k", value_column="v")
    op.open(RuntimeContext())
    op.incremental_state = True

    def feed(keys):
        op.process_batch(RecordBatch(
            {"k": keys, "v": np.ones(keys.size, np.float32)},
            timestamps=np.full(keys.size, 100, np.int64)))

    tmp = tempfile.mkdtemp(prefix="bench-incr-")
    try:
        storage = IncrementalCheckpointStorage(
            tmp, retain=rounds + 2, max_increments_per_base=rounds + 2,
            compact_in_background=False)
        for part in np.array_split(np.arange(n_keys), 8):
            feed(part)
        with snapshot_scope(1, incremental=True):
            storage.store(1, {"w": op.snapshot_state()})
        op.notify_checkpoint_complete(1)

        inc_bytes, cut_ms = [], []
        for cid in range(2, 2 + rounds):
            feed(rng.choice(n_keys, churn, replace=False).astype(np.int64))
            t0 = time.perf_counter()
            with snapshot_scope(cid, incremental=True):
                snap = op.snapshot_state()
            cut_ms.append((time.perf_counter() - t0) * 1000.0)
            if delta.tree_has_increment({"w": snap}):
                inc_bytes.append(delta.state_size(snap))
            storage.store(cid, {"w": snap})
            op.notify_checkpoint_complete(cid)

        full = op.snapshot_state()
        full_bytes = delta.state_size(full)
        last = storage.checkpoint_ids()[-1]
        t0 = time.perf_counter()
        restored = storage.load_latest()          # base + ordered replay
        op_r = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                                 SumAggregator(jnp.float32),
                                 key_column="k", value_column="v")
        op_r.open(RuntimeContext())
        op_r.restore_state(restored["w"])
        recovery_ms = (time.perf_counter() - t0) * 1000.0
        digest_match = _tree_eq(restored["w"], full) and _tree_eq(
            op_r.snapshot_state(), full)
        ratio = (max(inc_bytes) / full_bytes) if inc_bytes else None
        return {
            "metric": "incremental checkpoint bytes + recovery at "
                      f"{churn_frac:.0%} churn",
            "ok": bool(digest_match and inc_bytes),
            "n_keys": n_keys,
            "churn_keys": churn,
            "incremental_checkpoints": len(inc_bytes),
            "full_snapshot_bytes": int(full_bytes),
            "increment_bytes_max": int(max(inc_bytes)) if inc_bytes else None,
            "increment_bytes_mean": (round(sum(inc_bytes) / len(inc_bytes))
                                     if inc_bytes else None),
            "bytes_ratio": round(ratio, 4) if ratio is not None else None,
            "increments_per_base": storage.chain_length(last) - 1,
            "compactions": storage.compactions,
            "cut_ms_max": round(max(cut_ms), 2) if cut_ms else None,
            "recovery_ms": round(recovery_ms, 1),
            "digest_match": digest_match,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_incremental_budget(result: dict, budget: dict,
                             smoke: bool = False) -> list:
    """BENCH_BUDGET.json ``checkpoint_incremental`` gate.  Digest equality
    (chain restore == full snapshot) and the existence of incremental cuts
    gate UNCONDITIONALLY — a delta format that silently re-bases every cut
    or resolves to different state must never exit 0 because no byte
    ceiling was configured."""
    viol = []
    if not result.get("digest_match"):
        viol.append("incremental: chain-restored state is not "
                    "digest-identical to the full snapshot")
    floor = budget.get("min_incremental_checkpoints", 1)
    if result.get("incremental_checkpoints", 0) < floor:
        viol.append(f"incremental: {result.get('incremental_checkpoints')} "
                    f"delta cuts < floor {floor} — every cut re-based")
    cap = budget.get("max_bytes_ratio")
    ratio = result.get("bytes_ratio")
    if cap is not None and ratio is not None and ratio > cap:
        viol.append(f"incremental: delta bytes {ratio:.1%} of full "
                    f"snapshot > ceiling {cap:.0%} at "
                    f"{result.get('churn_keys')} churned keys")
    cap = budget.get("max_recovery_ms")
    rec = result.get("recovery_ms")
    if not smoke and cap is not None and rec is not None and rec > cap:
        viol.append(f"incremental: recovery {rec}ms > ceiling {cap}ms")
    return viol


# ONE diurnal implementation for --autoscale AND the scenario suite
# (ISSUE-15: twin generators drift) — promoted to testing/workload.py
from flink_tpu.testing.workload import DiurnalSource as _DiurnalSource  # noqa: E402


def run_autoscale_bench(args) -> dict:
    """``--autoscale``: the reactive autoscaler (ISSUE-14) under a diurnal
    load curve.  A stable-split :class:`_DiurnalSource` paces arrivals
    through a day curve while a seeded ``DelayBy`` on ``channel.recv``
    models a fixed per-dequeue consumer cost (so drain capacity scales
    with parallelism — the reason scale-out helps); the
    ``ReactiveAutoscaler`` watches the job's own backpressure gauges and
    rescales 2→4 at the peak and back down after it, each rescale an
    unaligned checkpoint with channel-state redistribution — no drain.
    Reports rescale count/latency, throughput recovery time after the
    scale-out, and records lost/duplicated (both MUST be 0), gated by
    BENCH_BUDGET.json ``rescale_cpu``."""
    import threading

    from flink_tpu.cluster.adaptive import (AutoscalerPolicy,
                                            ReactiveAutoscaler)
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
    from flink_tpu.testing import chaos
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    smoke = args.smoke
    n_records = args.records or (150_000 if smoke else 600_000)
    n_keys = min(args.keys, 1009 if smoke else 100_003)
    batch_size = 128
    span_ms = 20_000
    from flink_tpu.connectors.sinks import CollectSink
    sink = CollectSink()
    source = _DiurnalSource(n_records, n_keys, batch_size, span_ms,
                            peak_s=0.006, trough_s=0.025)

    def plan_factory(parallelism):
        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        (env.from_source(source)
         .assign_timestamps_and_watermarks(0, timestamp_column="t")
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1000))
         .sum("v").add_sink(sink))
        return env.get_stream_graph("autoscale-bench").to_plan()

    scale_out_depth, scale_in_depth = 12, 2
    policy = AutoscalerPolicy(min_parallelism=2, max_parallelism=4,
                              scale_out_queue_depth=scale_out_depth,
                              scale_in_queue_depth=scale_in_depth,
                              sustain_polls=3, cooldown_ms=1500.0)
    storage = InMemoryCheckpointStorage(retain=10)
    scaler = ReactiveAutoscaler(
        plan_factory, checkpoint_storage=storage, policy=policy,
        initial_parallelism=2, poll_interval_ms=25.0,
        checkpoint_interval_ms=50, alignment_timeout_ms=100.0,
        restart_attempts=4, job_timeout_s=600.0)
    inj = chaos.FaultInjector(seed=37)
    # the consumer-cost model: every dequeue pays a fixed cost, so drain
    # capacity is proportional to the number of consuming subtasks
    inj.inject("channel.recv", chaos.DelayBy(0.010))
    timeline = []
    stop = threading.Event()

    def watch():
        t_w0 = time.monotonic()
        while not stop.is_set():
            st = scaler.status()
            timeline.append((time.monotonic() - t_w0,
                             st["signals"].get("max_queue_depth", 0),
                             len(st["parallelism_path"]),
                             st["last_rescale_duration_ms"]))
            time.sleep(0.05)

    t0 = time.monotonic()
    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    with chaos.installed(inj):
        scaler.start()
        scaler.join(timeout_s=600)
    stop.set()
    wall_ms = (time.monotonic() - t0) * 1000.0
    st = scaler.status()

    # exactly-once accounting: per-key window sums vs the generated data
    expected = {k: s for k, (_c, s) in source.expected_per_key().items()}
    got: dict = {}
    for r in sink.rows():
        got[int(r["k"])] = got.get(int(r["k"]), 0.0) + float(r["v"])
    lost = dup = 0.0
    for k in set(expected) | set(got):
        d = expected.get(k, 0.0) - got.get(k, 0.0)
        if d > 0:
            lost += d
        else:
            dup -= d

    # throughput recovery: time from the first rescale COMPLETING (first
    # output of the new deployment — last_rescale_duration_ms appears)
    # until queue depth is back under the scale-in threshold: the new
    # parallelism has drained the peak's backlog
    recovery_ms = None
    t_out = None
    for t, depth, path_len, dur in timeline:
        if t_out is None:
            if path_len >= 2 and dur is not None:
                t_out = t
            continue
        if depth <= scale_in_depth:
            recovery_ms = round((t - t_out) * 1000.0, 1)
            break
    if t_out is not None and recovery_ms is None:
        recovery_ms = round((timeline[-1][0] - t_out) * 1000.0, 1)

    finished = scaler.state == "Finished"
    ok = (finished and lost == 0 and dup == 0 and st["rescales"] >= 1)
    return {
        "metric": "reactive autoscaler under a diurnal load curve",
        "ok": bool(ok),
        "state": scaler.state,
        "error": scaler.error,
        "records": n_records,
        "keys": n_keys,
        "rescales": st["rescales"],
        "rollbacks": st["rollbacks"],
        "retriggers": st["retriggers"],
        "parallelism_path": st["parallelism_path"],
        "rescale_latency_ms": st["last_rescale_duration_ms"],
        "recovery_ms": recovery_ms,
        "records_lost": int(lost),
        "records_duplicated": int(dup),
        "records_per_sec": round(n_records / max(wall_ms / 1000.0, 1e-9)),
        "wall_ms": round(wall_ms, 1),
    }


def check_rescale_budget(result: dict, budget: dict,
                         smoke: bool = False) -> list:
    """BENCH_BUDGET.json ``rescale_cpu`` gate for ``--autoscale``.
    Exactly-once (zero lost, zero duplicated records) and job completion
    gate UNCONDITIONALLY — a rescale that loses records must never exit 0
    because no perf ceiling was configured."""
    viol = []
    if result.get("state") != "Finished":
        viol.append(f"autoscaled job did not finish: "
                    f"{result.get('state')} ({result.get('error')})")
    lost = result.get("records_lost")
    if lost != 0:
        viol.append(f"records_lost {lost} != 0 — rescale dropped records")
    dup = result.get("records_duplicated")
    if dup != 0:
        viol.append(f"records_duplicated {dup} != 0 — rescale replayed "
                    f"records twice")
    floor = budget.get("min_rescales", 1)
    if result.get("rescales", 0) < floor:
        viol.append(f"rescales {result.get('rescales')} < floor {floor} — "
                    f"the autoscaler never reacted to the load curve")
    cap = budget.get("max_rollbacks")
    if cap is not None and result.get("rollbacks", 0) > cap:
        viol.append(f"rollbacks {result.get('rollbacks')} > ceiling {cap}")
    cap = budget.get("max_rescale_latency_ms")
    lat = result.get("rescale_latency_ms")
    if cap is not None and lat is not None and lat > cap:
        viol.append(f"rescale latency {lat}ms > ceiling {cap}ms")
    cap = budget.get("max_recovery_ms")
    rec = result.get("recovery_ms")
    if not smoke and cap is not None and rec is not None and rec > cap:
        viol.append(f"throughput recovery {rec}ms > ceiling {cap}ms")
    return viol


def run_scenario_bench(args) -> dict:
    """``--scenario <name>|all``: the scenario suite (ISSUE-15) — named
    end-to-end exactly-once applications under the shared diurnal load
    curve.  Each scenario runs its FAULTED leg (reactive autoscaler,
    consumer-cost backpressure, nemeses armed at the peak: worker kill,
    SlowConsumer, KillDuringRescale, and — full runs — WedgedDevice;
    routed binary queryable readers at a paced QPS) plus an unfaulted
    CONTROL leg over a bit-identical stream, then verifies the committed
    transactional output is exactly-once: zero lost, zero duplicated,
    digest-identical to the control, scenario cross-checks clean.  With
    ``--check`` each scenario gates against its own BENCH_BUDGET.json
    section (``scenario_fraud_cpu`` / ``scenario_session_cpu`` /
    ``scenario_feature_cpu``)."""
    from flink_tpu.scenarios import SCENARIOS, ScenarioHarness, get_scenario

    names = (list(SCENARIOS) if args.scenario == "all"
             else [args.scenario])
    results = []
    for name in names:
        harness = ScenarioHarness(
            get_scenario(name), smoke=args.smoke,
            records=args.records or None,
            full_nemeses=not args.smoke)
        results.append(harness.run())
    return {
        "metric": "scenario suite: exactly-once applications under a "
                  "diurnal load curve",
        "ok": all(r["ok"] for r in results),
        "scenarios": results,
    }


def check_scenario_budget(result: dict, budget: dict,
                          smoke: bool = False) -> list:
    """BENCH_BUDGET.json gate for ONE scenario result.  Exactly-once
    gates UNCONDITIONALLY (even smoke, even with an empty budget
    section): records lost or duplicated, a committed digest differing
    from the unfaulted control, a failed cross-check, or an empty
    committed output must never exit 0 because no perf floor was
    configured."""
    name = result.get("scenario", "?")
    viol = []
    if result.get("state") != "Finished":
        viol.append(f"{name}: faulted job did not finish: "
                    f"{result.get('state')} ({result.get('error')})")
    if result.get("control_state") != "Finished":
        viol.append(f"{name}: control job did not finish: "
                    f"{result.get('control_state')} "
                    f"({result.get('control_error')})")
    lost = result.get("records_lost")
    if lost != 0:
        viol.append(f"{name}: records_lost {lost} != 0 — committed output "
                    f"dropped rows under chaos")
    dup = result.get("records_duplicated")
    if dup != 0:
        viol.append(f"{name}: records_duplicated {dup} != 0 — committed "
                    f"output replayed rows twice")
    if not result.get("digest_match"):
        viol.append(f"{name}: committed-sink digest differs from the "
                    f"unfaulted control")
    for v in result.get("cross_check_violations", []):
        viol.append(f"{name}: {v}")
    if sum(result.get("committed_rows", {}).values()) <= 0:
        viol.append(f"{name}: no committed output rows")
    floor = budget.get("min_rescales", 1)
    if result.get("rescales", 0) < floor:
        viol.append(f"{name}: rescales {result.get('rescales')} < floor "
                    f"{floor} — the autoscaler never reacted to the "
                    f"diurnal curve")
    cap = budget.get("max_rollbacks")
    if cap is not None and result.get("rollbacks", 0) > cap:
        viol.append(f"{name}: rollbacks {result.get('rollbacks')} > "
                    f"ceiling {cap}")
    if not smoke:
        floor = budget.get("min_peak_rps")
        peak = result.get("peak_records_per_sec")
        if floor is not None and (peak or 0.0) < floor:
            viol.append(f"{name}: sustained peak {peak} rec/s < floor "
                        f"{floor}")
        cap = budget.get("max_p99_ms")
        p99 = result.get("latency_p99_ms")
        if cap is not None and p99 is not None and p99 > cap:
            viol.append(f"{name}: end-to-end p99 {p99}ms > ceiling "
                        f"{cap}ms")
        floor = budget.get("min_lookups_per_sec")
        q = result.get("queryable") or {}
        if floor is not None and q:
            lps = q.get("lookups_per_sec", 0.0)
            if lps < floor:
                viol.append(f"{name}: queryable reads {lps}/s < floor "
                            f"{floor}/s")
    return viol


def run_ha_kill_bench(args) -> dict:
    """``--ha-kill``: coordinator high availability under fire (ISSUE-20).
    Leader A runs a scenario under a FileHaStore lease; a
    ``KillCoordinator`` nemesis fails A's lease renewal at the diurnal
    peak (loud demotion — A keeps executing as a ZOMBIE); standby B
    acquires the lease at epoch + 1, proves the zombie's stale-epoch
    checkpoint completions are fenced by the HA store, recovers the job
    from the completed-checkpoint pointer (increment chains included) and
    finishes it.  Committed output must be exactly-once and
    digest-identical to an unfaulted control; with ``--check`` gates
    against BENCH_BUDGET.json ``ha_cpu``."""
    from flink_tpu.scenarios import ScenarioHarness, get_scenario

    name = args.scenario or "fraud_detection"
    harness = ScenarioHarness(get_scenario(name), smoke=args.smoke,
                              records=args.records or None)
    result = harness.run_ha_kill()
    return {
        "metric": "coordinator HA: leader kill at the peak, epoch-fenced "
                  "takeover from the HA store",
        "ok": bool(result.get("ok")),
        "ha_kill": result,
    }


def check_ha_budget(result: dict, budget: dict, smoke: bool = False) -> list:
    """BENCH_BUDGET.json ``ha_cpu`` gate for one ``--ha-kill`` result.
    Exactly-once and the fencing probes gate UNCONDITIONALLY (even smoke,
    even with an empty budget section): a zombie ex-leader completing a
    checkpoint or committing a 2PC transaction, lost/duplicated rows, or
    a digest mismatch must never exit 0 because no ceiling was
    configured.  The recovery-time ceiling is full-run only (smoke hosts
    jitter too much for a wall-clock gate)."""
    name = result.get("scenario", "?")
    viol = []
    if result.get("state") != "FINISHED":
        viol.append(f"{name}: recovered job did not finish: "
                    f"{result.get('state')}")
    if result.get("control_state") != "Finished":
        viol.append(f"{name}: control job did not finish: "
                    f"{result.get('control_state')} "
                    f"({result.get('control_error')})")
    epochs = result.get("leader_epochs") or []
    if len(epochs) != 2 or epochs[1] <= epochs[0]:
        viol.append(f"{name}: takeover did not advance the leader epoch "
                    f"({epochs})")
    if not result.get("stale_pointer_rejected"):
        viol.append(f"{name}: zombie ex-leader's checkpoint completion "
                    f"was NOT fenced by the HA store")
    if not result.get("stale_commit_fenced"):
        viol.append(f"{name}: a 2PC commit under the stale epoch was NOT "
                    f"fenced")
    lost = result.get("records_lost")
    if lost != 0:
        viol.append(f"{name}: records_lost {lost} != 0 across the "
                    f"coordinator kill")
    dup = result.get("records_duplicated")
    if dup != 0:
        viol.append(f"{name}: records_duplicated {dup} != 0 across the "
                    f"coordinator kill")
    if not result.get("digest_match"):
        viol.append(f"{name}: committed-sink digest differs from the "
                    f"unfaulted control")
    if sum(result.get("committed_rows", {}).values()) <= 0:
        viol.append(f"{name}: no committed output rows")
    if not smoke:
        cap = budget.get("max_recovery_ms")
        rec = result.get("recovery_ms")
        if cap is not None and rec is not None and rec > cap:
            viol.append(f"{name}: recovery {rec}ms > ceiling {cap}ms "
                        f"(demotion -> new-epoch checkpoint completed)")
    return viol


def _cep_pattern(window_ms: int):
    """Fraud-detection shape (examples/fraud_detection.py as a PATTERN):
    a small 'bait' transaction followed by a large 'strike' on the same
    key within 4 windows."""
    from flink_tpu.cep import Pattern

    return (Pattern.begin("small")
            .where(lambda c: np.asarray(c["v"]) < 30.0)
            .followed_by("large")
            .where(lambda c: np.asarray(c["v"]) > 570.0)
            .within(4 * window_ms))


def _cep_batches(n_records: int, n_keys: int, batch_size: int,
                 window_ms: int, seed: int = 23):
    rng = np.random.default_rng(seed)
    batches = []
    t = 0
    for lo in range(0, n_records, batch_size):
        b = min(batch_size, n_records - lo)
        keys = rng.integers(0, n_keys, b).astype(np.int64)
        vals = (rng.random(b) * 600.0).astype(np.float64)
        ts = t + np.sort(rng.integers(0, window_ms, b)).astype(np.int64)
        t += window_ms
        batches.append((keys, vals, ts))
    return batches


def run_cep_bench(args) -> dict:
    """``--cep``: the vectorized CEP engine (ISSUE-8 tentpole) on a
    fraud-detection-style pattern over the 1M-key stream.  Reports
    events/sec + matches/sec + the partial-match high-water mark for the
    batched kernel, the interpreted NFA's rate on the same stream (time-
    budgeted — it is the per-event Python loop being replaced), the
    engine ``auto`` calibration picked on this backend, and a small-prefix
    equivalence check (identical matches, identical order).  With
    ``--check`` the result gates against BENCH_BUDGET.json ``cep_cpu``."""
    from flink_tpu.cep import CepOperator
    from flink_tpu.core.batch import RecordBatch, Watermark

    n_records = args.records or (1 << 17 if args.smoke else 1 << 22)
    n_keys = min(args.keys, n_records)
    window_ms = args.window_ms
    batches = _cep_batches(n_records, n_keys, args.batch_size, window_ms)
    pattern = _cep_pattern(window_ms)
    select = (lambda m: {"k": m["small"][0]["k"],
                         "amount": m["large"][0]["v"]})

    def one_pass(mode, budget_s=None):
        op = CepOperator(pattern, "k", select, vectorized=mode)
        t0 = time.perf_counter()
        n = matches = 0
        for keys, vals, ts in batches:
            out = op.process_batch(
                RecordBatch({"k": keys, "v": vals}, timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
            matches += sum(len(b) for b in out if hasattr(b, "columns"))
            n += keys.size
            if budget_s and time.perf_counter() - t0 > budget_s:
                break
        if not budget_s:
            tail = op.end_input()
            matches += sum(len(b) for b in tail if hasattr(b, "columns"))
        elapsed = time.perf_counter() - t0
        return n / elapsed, matches / elapsed, matches, op.cep_stats()

    # small-prefix equivalence: both engines, identical matches in order
    def mini_rows(mode):
        op = CepOperator(pattern, "k", select, vectorized=mode)
        rows = []
        for keys, vals, ts in _cep_batches(1 << 14, 4096, 4096, window_ms):
            out = op.process_batch(
                RecordBatch({"k": keys, "v": vals}, timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
            for b in out:
                for i in range(len(b)):
                    rows.append((int(np.asarray(b.column("k"))[i]),
                                 float(np.asarray(b.column("amount"))[i]),
                                 int(np.asarray(b.timestamps)[i])))
        return rows

    equivalence_ok = mini_rows("on") == mini_rows("off")

    vec = _best_of(lambda: one_pass("on"), 2 if args.smoke else 3)
    interp = one_pass("off", budget_s=5.0 if args.smoke else 30.0)
    auto_op = CepOperator(pattern, "k", select, vectorized="auto")
    k0, v0, t0 = batches[0]
    auto_op.process_batch(RecordBatch({"k": k0[:1024], "v": v0[:1024]},
                                      timestamps=t0[:1024]))
    auto_engine = auto_op.cep_stats()["engine"]

    eps, mps, matches, stats = vec
    i_eps, i_mps, _im, _is = interp
    detail = {
        "events_per_sec": round(eps, 1),
        "matches": matches,
        "partials_high_water": stats["partials_high_water"],
        "interpreted_events_per_sec": round(i_eps, 1),
        "interpreted_matches_per_sec": round(i_mps, 1),
        "speedup_vs_interpreted": round(mps / i_mps, 2) if i_mps else None,
        "auto_engine": auto_engine,
        "equivalence_ok": equivalence_ok,
        "n_records": n_records,
        "n_keys": n_keys,
        "vectorized_drains": stats["vectorized_drains"],
        "degraded": stats["degraded"],
    }
    return {
        "metric": f"matches/sec (CEP fraud pattern, {n_keys} keys, "
                  f"vectorized NFA kernel)",
        "value": round(mps, 1),
        "unit": "matches/sec",
        "ok": equivalence_ok and stats["degraded"] == 0,
        "details": detail,
    }


def check_cep_budget(result: dict, budget: dict, smoke: bool = False) -> list:
    """``--cep`` result vs the BENCH_BUDGET ``cep_cpu`` section: a
    matches/sec floor (full runs), a speedup-vs-interpreted floor (the
    acceptance bar — the batched kernel must beat the per-event Python
    loop; relaxed at smoke size where fixed costs dominate), and the
    equivalence check (never exit 0 on divergent matches)."""
    viol = []
    d = result["details"]
    if not d.get("equivalence_ok"):
        viol.append("vectorized-vs-interpreted equivalence check failed")
    floor = budget.get("min_matches_per_sec")
    if floor is not None and not smoke and result["value"] < floor:
        viol.append(f"matches/sec {result['value']:.0f} < floor {floor:.0f}")
    sp = d.get("speedup_vs_interpreted")
    sp_floor = budget.get("min_speedup_smoke" if smoke
                          else "min_speedup_vs_interpreted")
    if sp_floor is not None and sp is None:
        # the interpreted leg produced no matches: the A/B measured
        # nothing, which must not read as "bar met"
        viol.append("speedup vs interpreted unmeasured (interpreted pass "
                    "recorded zero matches) — the acceptance bar cannot "
                    "be skipped")
    elif sp is not None and sp_floor is not None and sp < sp_floor:
        viol.append(f"speedup vs interpreted {sp} < floor {sp_floor} "
                    f"(the batched kernel is not paying for itself)")
    if d.get("auto_engine") not in ("vectorized", "interpreted"):
        viol.append(f"auto calibration resolved no engine: "
                    f"{d.get('auto_engine')!r}")
    return viol


def run_queryable_bench(args) -> dict:
    """``--queryable``: the serving tier at production QPS (ISSUE-13)
    against a RUNNING 1M-key window job.  One pass drains the stream with
    no read load (baseline records/sec), a second pass drains the SAME
    stream while ``--qps-clients`` pooled clients sustain
    ``--qps-target`` aggregate lookups/sec through the BINARY COLUMNAR
    wire protocol with client-side key-group routing — alternating
    ``live`` and ``checkpoint`` consistency.  Reports lookups/sec,
    client-side p50/p99 AND the server-side service-time percentiles
    (lookup + serialization measured in the handler — the honest number
    on a GIL-loaded box), protocol + routing mode, cache hit rate, the
    replicas' worst observed lag, the job's throughput under load as a
    FRACTION of unloaded (the <10% tax acceptance), a live-equality
    check (wire values == the view's fire-time values) and a
    binary==JSON answer-equality check.  With ``--check`` gates against
    BENCH_BUDGET.json ``queryable_cpu``."""
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.queryable import (QueryableStateClientPool,
                                     QueryableStateService,
                                     QueryableStateSpec)
    from flink_tpu.queryable import wire as qwire

    n_records = args.records or (1 << 17 if args.smoke else 1 << 22)
    n_keys = min(args.keys, n_records)
    window_ms = args.window_ms
    # smoke shrinks the batch size too: the checkpoint feed must run at
    # least a few times per pass or the replica/staleness leg measures
    # nothing
    batch_size = min(args.batch_size, 1 << 14) if args.smoke \
        else args.batch_size
    batches = make_batches(n_records, n_keys, batch_size, window_ms)
    ckpt_every = max(1, min(args.checkpoint_every, len(batches) // 4))
    # client count trades per-request RTT for in-flight concurrency: the
    # drain's jitted megastep holds the GIL in multi-ms stretches, so a
    # single request's round trip can span several dispatch windows —
    # sustained qps = in-flight / RTT, and the fleet is paced to the same
    # aggregate target regardless of its size
    n_clients = args.qps_clients or (2 if args.smoke else 16)
    batch_keys = args.qps_batch_keys
    qps_target = args.qps_target
    # sustained-rate pacing: each client fires every `interval` seconds so
    # the fleet lands on the aggregate target — the acceptance is "the
    # target RATE sustained with <10% hot-path tax", not "max rate at any
    # tax" (an unthrottled fleet measures GIL contention, not serving)
    interval = (n_clients * batch_keys / qps_target) if qps_target else 0.0

    # the serving window must be long enough to SUSTAIN the target rate
    # (the one-dispatch job drains 4M records in well under a second):
    # repeat the stream with advancing timestamps — same keys (warm steady
    # state), fresh windows every repeat, live fires throughout
    repeats = 1 if args.smoke else 8
    max_ts = max(int(ts.max()) for _k, _v, ts in batches)
    ts_span = ((max_ts // window_ms) + 2) * window_ms
    # checkpoint cadence spans the WHOLE run (~4 checkpoints however many
    # repeats): each 1M-key ingest is real background work on the feed
    # thread, and production checkpoints are time-based, not
    # per-2M-records
    ckpt_every = max(ckpt_every, (len(batches) * repeats) // 4 or 1)

    def drain(op, svc=None, n_repeats=1):
        """The job under test: the standard drain loop over ``n_repeats``
        timestamp-shifted passes of the stream (same keys — warm steady
        state; fresh windows every repeat), snapshotting into the serving
        tier's checkpoint feed (the MiniCluster _complete_checkpoint
        path, inlined)."""
        cid = 0
        step = 0
        t0 = time.perf_counter()
        for r in range(n_repeats):
            off = r * ts_span
            for k, v, ts in batches:
                tso = ts + off if off else ts
                op.process_batch(RecordBatch({"k": k, "v": v},
                                             timestamps=tso))
                op.process_watermark(Watermark(int(tso.max()) - 1))
                step += 1
                if svc is not None and step % ckpt_every == 0:
                    cid += 1
                    op.prepare_snapshot_pre_barrier()
                    snap = op.snapshot_state()
                    svc.on_checkpoint_complete(
                        cid, {"win": {"subtasks": [{"operator": snap}]}})
                    op.notify_checkpoint_complete(cid)
        op.flush_pipeline()
        elapsed = time.perf_counter() - t0
        op.end_input()
        return n_records * n_repeats / elapsed, cid

    # warm-up: one throwaway prefix drain + snapshot so pass 1 measures
    # the job, not XLA compiles / process-wide sync+superbatch
    # calibration / allocator warm-up (pass ordering must not bias the
    # under-load-vs-unloaded fraction)
    warm = _build_op(window_ms, "host", args.device_sync,
                     pipeline_depth=args.pipeline_depth,
                     native_shards=args.native_shards,
                     device_probe=args.device_probe)
    for k, v, ts in batches[: max(1, len(batches) // 8)]:
        warm.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        warm.process_watermark(Watermark(int(ts.max()) - 1))
    warm.flush_pipeline()
    warm.prepare_snapshot_pre_barrier()
    warm.snapshot_state()
    warm.end_input()
    del warm

    # a serving process trades a sliver of drain throughput for request
    # latency: the default 5ms GIL switch interval parks a handler thread
    # for milliseconds per slice behind the drain loop.  Applied to BOTH
    # passes so the fraction stays apples-to-apples.
    switch0 = sys.getswitchinterval()
    sys.setswitchinterval(0.001)

    # interleaved rounds of (unloaded leg, loaded leg), best of each —
    # symmetric, so this class of vCPU host's 10%+ run-to-run steal noise
    # hits both sides of the under-load fraction equally.  EVERY leg runs
    # the IDENTICAL job — queryable views published, checkpoints
    # snapshotted and replica-ingested — so the fraction isolates the
    # READ load, not the checkpoint stream the job runs either way.
    rounds = 1 if args.smoke else 2

    def _leg_op():
        return _build_op(window_ms, "host", args.device_sync,
                         pipeline_depth=args.pipeline_depth,
                         native_shards=args.native_shards,
                         device_probe=args.device_probe, queryable="agg")

    # ONE serving tier + server for the whole bench: loaded legs
    # re-register their op's live view (register_views replaces), the
    # replica keeps ingesting whichever loaded leg is running
    import jax.numpy as jnp

    from flink_tpu.core.functions import SumAggregator
    svc = QueryableStateService()
    svc.add_replica("agg", QueryableStateSpec("agg", "win", "k",
                                              SumAggregator(jnp.float32)))
    server = svc.start_server()

    # the client fleet runs OUT-OF-PROCESS, like production readers: a
    # client thread inside the job process measures GIL scheduling, not
    # serving.  Only the server (its handler threads) shares the job's
    # process — that contention IS the hot-path tax under test.  Clients
    # pause between loaded legs (stdio go/pause protocol).
    import subprocess as _sp
    bench_path = os.path.abspath(__file__)
    cprocs = []
    for c in range(n_clients):
        cenv = dict(os.environ)
        # pin CPU in the client processes: they never run jax work, but
        # bench.py's import-time wedged-accelerator guard probes the
        # tunnel UNLESS JAX_PLATFORMS=cpu — 16 clients each paying a
        # (possibly minutes-long) probe would dwarf the bench
        cenv["JAX_PLATFORMS"] = "cpu"
        cprocs.append(_sp.Popen(
            [sys.executable, bench_path, "--_qps-client",
             "--_qps-host", str(server.host),
             "--_qps-port", str(server.port),
             "--_qps-seed", str(100 + c),
             "--_qps-interval-us", str(interval * 1e6),
             "--qps-batch-keys", str(batch_keys),
             "--keys", str(n_keys)],
            stdin=_sp.PIPE, stdout=_sp.PIPE, text=True, env=cenv))
    counts = {"lookups": 0, "errors": 0, "max_lag": 0, "routed_batches": 0}
    lat_ms: list = []
    ready = 0
    for p in cprocs:
        line = p.stdout.readline()
        if line.strip() == "READY":
            ready += 1
    if ready < n_clients:
        counts["errors"] += n_clients - ready

    def _fleet(cmd: str) -> None:
        for p in cprocs:
            try:
                p.stdin.write(cmd + "\n")
                p.stdin.flush()
            except OSError:
                pass

    rps_no_load = 0.0
    rps_load = 0.0
    q_elapsed = 0.0
    n_ckpts = 0
    op = None
    for _round in range(rounds):
        # unloaded leg
        op0 = _leg_op()
        svc0 = QueryableStateService()
        svc0.register_views("agg", [op0.queryable_view()], 1, 128)
        svc0.add_replica("agg", QueryableStateSpec("agg", "win", "k",
                                                   op0.agg))
        rps, _ = drain(op0, svc0, n_repeats=repeats)
        svc0.drain_feed()
        svc0.close()
        rps_no_load = max(rps_no_load, rps)
        del op0
        # loaded leg: same job + the paced client fleet
        op = _leg_op()
        svc.register_views("agg", [op.queryable_view()], 1, 128)
        q_t0 = time.perf_counter()
        _fleet("go")
        rps, cids = drain(op, svc, n_repeats=repeats)
        _fleet("pause")
        q_elapsed += time.perf_counter() - q_t0
        rps_load = max(rps_load, rps)
        n_ckpts += cids
        if _round < rounds - 1:
            op.end_input()
    _fleet("stop")
    for p in cprocs:
        try:
            out, _ = p.communicate(timeout=60)
        except _sp.TimeoutExpired:
            p.kill()
            counts["errors"] += 1
            continue
        stats_line = next((ln for ln in out.splitlines()
                           if ln.startswith("STATS ")), None)
        if stats_line is None:
            counts["errors"] += 1
            continue
        st = json.loads(stats_line[len("STATS "):])
        lat_ms.extend(st["lat_ms"])
        counts["lookups"] += st["lookups"]
        counts["errors"] += st["errors"]
        counts["max_lag"] = max(counts["max_lag"], st["max_lag"])
        counts["routed_batches"] += st["routed_batches"]

    # live equality over the wire: served values must equal the view's
    # fire-time values EXACTLY (the server adds serialization, not math);
    # and the binary answer must be bit-identical to the JSON answer for
    # the same keys (two encodings, one contract)
    view = op.queryable_view()
    jpool = QueryableStateClientPool(server.host, server.port)  # pure JSON
    bpool = QueryableStateClientPool(server.host, server.port,
                                     protocol="binary", routing=True)
    rngq = np.random.default_rng(5)
    sample = rngq.integers(0, n_keys, 256).astype(int).tolist()
    json_ans = jpool.get_batch("agg", sample, consistency="live")
    vf, vv, _vt = view.lookup_batch(np.asarray(sample, np.int64))
    live_equal = (json_ans["found"] == vf.tolist()
                  and all((w is None and d is None) or w == d
                          for w, d in zip(json_ans["values"], vv)))
    bin_json_equal = True
    for cons in ("live", "checkpoint"):
        j = jpool.get_batch("agg", sample, consistency=cons)
        bf, bc, _bt = bpool.get_batch_columnar(
            "agg", np.asarray(sample, np.int64), consistency=cons)
        bvals = qwire.values_from_columnar(bf, bc)
        if j["found"] != bf.tolist() or any(
                not ((w is None and d is None) or w == d)
                for w, d in zip(j["values"], bvals)):
            bin_json_equal = False
    jpool.close()
    bpool.close()
    svc.drain_feed()
    final = svc.stats()
    svc.close()
    sys.setswitchinterval(switch0)

    lat = np.asarray(lat_ms) if lat_ms else np.zeros(1)
    qps = counts["lookups"] / max(q_elapsed, 1e-9)
    detail = {
        "n_records": n_records,
        "n_keys": n_keys,
        "clients": n_clients,
        "keys_per_request": batch_keys,
        "protocol": "binary",
        "routing": "client" if counts["routed_batches"] else "server",
        "qps_target": qps_target,
        "lookups": counts["lookups"],
        "lookup_errors": counts["errors"],
        "lookups_per_sec": round(qps, 1),
        "lookup_p50_ms": round(float(np.percentile(lat, 50)), 2),
        "lookup_p99_ms": round(float(np.percentile(lat, 99)), 2),
        # server-side service time (lookup + serialization in the
        # handler): the client-side p99 above also measures GIL stalls of
        # this 2-vCPU box; this one measures the server
        "serve_p50_ms": final.get("serve_p50_ms"),
        "serve_p99_ms": final.get("serve_p99_ms"),
        "cache_hit_rate": final.get("cache_hit_rate", 0.0),
        "records_per_sec_no_load": round(rps_no_load, 1),
        "records_per_sec_under_load": round(rps_load, 1),
        "rps_under_load_frac": round(rps_load / max(rps_no_load, 1e-9), 3),
        "checkpoints_fed": n_ckpts,
        "max_replica_lag_checkpoints": max(
            counts["max_lag"], final["replica_lag_checkpoints"]),
        "live_equality_ok": live_equal,
        "binary_json_equal_ok": bin_json_equal,
        "server_lookups_total": final["lookups_total"],
    }
    return {
        "metric": f"batched lookups/sec ({n_clients} clients x "
                  f"{batch_keys}-key binary columnar requests, "
                  f"client-routed, against the running "
                  f"{n_keys}-key window job, live+checkpoint)",
        "value": round(qps, 1),
        "unit": "lookups/sec",
        "ok": live_equal and bin_json_equal and counts["errors"] == 0,
        "details": detail,
    }


def _qps_client_main(args) -> int:
    """Hidden ``--_qps-client`` worker: ONE out-of-process queryable
    client of the ``--queryable`` bench.  Binary columnar protocol,
    client-side key-group routing, constant-arrival-rate pacing (the wrk2
    model: requests are DUE on a fixed schedule; after a stall the client
    catches up to a bounded backlog so the offered rate stays the
    target).  Parent protocol over stdio: prints ``READY``, then cycles
    on ``go``/``pause`` lines (the bench interleaves loaded and unloaded
    legs), stops on ``stop``/EOF and prints ``STATS <json>``."""
    import threading as _th

    from flink_tpu.queryable import QueryableStateClientPool

    state = {"cmd": "wait"}

    def _stdin_watch():
        for line in sys.stdin:
            cmd = line.strip()
            if cmd in ("go", "pause", "stop"):
                state["cmd"] = cmd
                if cmd == "stop":
                    return
        state["cmd"] = "stop"

    _th.Thread(target=_stdin_watch, daemon=True).start()
    pool = QueryableStateClientPool(args._qps_host, args._qps_port,
                                    size=2, retries=1,
                                    protocol="binary", routing=True)
    rng = np.random.default_rng(args._qps_seed)
    interval = args._qps_interval_us / 1e6
    batch_keys = args.qps_batch_keys
    n_keys = args.keys
    backlog_cap = max(4, int(1.0 / interval)) if interval else 0
    print("READY", flush=True)
    lat, lookups, errors, max_lag = [], 0, 0, 0
    i = 0
    while state["cmd"] != "stop":
        if state["cmd"] != "go":
            time.sleep(0.005)
            continue
        # entering a loaded leg: fresh schedule (pause time is not debt)
        t_start = time.perf_counter() + (rng.uniform(0, interval)
                                         if interval else 0.0)
        fired = 0
        while state["cmd"] == "go":
            if interval:
                due = (time.perf_counter() - t_start) / interval
                if fired >= due:
                    time.sleep(min((fired - due + 1) * interval, 0.02))
                    continue
                # bounded catch-up: after a stall (a 1M-key snapshot
                # stretch holds the server's GIL for ~300ms) the client
                # replays up to ONE SECOND of missed schedule, so the
                # offered rate averages the target instead of
                # target x uptime — any older backlog is dropped rather
                # than burst at the window's end
                fired = max(fired + 1, int(due) - backlog_cap)
            keys = rng.integers(0, n_keys, batch_keys)    # stays int64
            cons = "checkpoint" if i % 2 else "live"
            i += 1
            t0 = time.perf_counter()
            try:
                _f, _c, tags = pool.get_batch_columnar("agg", keys,
                                                       consistency=cons)
            except (RuntimeError, ConnectionError):
                errors += 1
                continue
            if len(lat) < 20000:
                lat.append(round((time.perf_counter() - t0) * 1e3, 4))
            lookups += batch_keys
            max_lag = max(max_lag,
                          tags.get("replica_lag_checkpoints") or 0)
    routed = pool.stats["routed_batches"]
    pool.close()
    print("STATS " + json.dumps(
        {"lookups": lookups, "errors": errors, "max_lag": max_lag,
         "routed_batches": routed, "lat_ms": lat}), flush=True)
    return 0


def check_queryable_budget(result: dict, budget: dict,
                           smoke: bool = False) -> list:
    """``--queryable`` vs BENCH_BUDGET ``queryable_cpu``: a lookups/sec
    floor and a hot-path throughput-tax floor as a FRACTION of unloaded
    (full runs — smoke sizes are dominated by fixed costs), a client-side
    p99 ceiling, a replica staleness ceiling, and the unconditional
    equality checks — live wire values == the view's fire-time values,
    and binary answers == JSON answers — which never exit 0 on a
    divergence, smoke included."""
    viol = []
    d = result["details"]
    if not d.get("live_equality_ok"):
        viol.append("live reads over the wire diverge from the view's "
                    "fire-time values")
    if "binary_json_equal_ok" in d and not d["binary_json_equal_ok"]:
        viol.append("binary columnar answers diverge from JSON answers "
                    "for the same keys (two encodings must share one "
                    "contract)")
    if d.get("lookup_errors"):
        viol.append(f"{d['lookup_errors']} lookup requests failed after "
                    f"pooled-client retries")
    floor = budget.get("min_lookups_per_sec")
    if floor is not None and not smoke and result["value"] < floor:
        viol.append(f"lookups/sec {result['value']:.0f} < floor {floor:.0f}")
    p99_cap = budget.get("max_p99_ms")
    if p99_cap is not None and d["lookup_p99_ms"] > p99_cap:
        viol.append(f"lookup p99 {d['lookup_p99_ms']}ms > ceiling "
                    f"{p99_cap}ms")
    serve_cap = budget.get("max_serve_p99_ms")
    if serve_cap is not None and d.get("serve_p99_ms") is not None \
            and d["serve_p99_ms"] > serve_cap:
        viol.append(f"server-side serve p99 {d['serve_p99_ms']}ms > "
                    f"ceiling {serve_cap}ms")
    lag_cap = budget.get("max_replica_lag_checkpoints")
    if lag_cap is not None \
            and d["max_replica_lag_checkpoints"] > lag_cap:
        viol.append(f"replica lag {d['max_replica_lag_checkpoints']} "
                    f"checkpoints > ceiling {lag_cap} (the replica feed "
                    f"is not keeping up with the checkpoint stream)")
    # hot-path non-interference, as a fraction of the unloaded run (the
    # ISSUE-13 acceptance: under-load throughput >= 0.90 of unloaded)
    frac_floor = budget.get("min_rps_under_load_frac")
    if frac_floor is not None and not smoke \
            and d["rps_under_load_frac"] < frac_floor:
        viol.append(f"records/sec under query load is "
                    f"{d['rps_under_load_frac']:.3f} of unloaded < floor "
                    f"{frac_floor} (reads are taxing the hot path)")
    # legacy absolute floor, honored when a budget still carries it
    rps_floor = budget.get("min_rps_under_load")
    if rps_floor is not None and not smoke \
            and d["records_per_sec_under_load"] < rps_floor:
        viol.append(f"records/sec under query load "
                    f"{d['records_per_sec_under_load']:.0f} < floor "
                    f"{rps_floor:.0f} (reads are stealing the hot path)")
    return viol


def run_mesh_bench(args) -> dict:
    """``--mesh-devices N``: the sharded hot path as ONE logical operator
    over an N-device mesh (forced host devices on CPU — see
    ``_early_mesh_device_flags``).  Reports records/sec/**pod** alongside
    records/sec/chip, the per-shard probe_mirror breakdown (the wall
    decomposed into N independent probes), and the restore+replay digest
    check — the multi-chip twin of the headline run."""
    import jax

    D = args.mesh_devices
    avail = len(jax.devices())
    if avail < D:
        return {"metric": "records/sec/pod (mesh sharded hot path)",
                "ok": False,
                "error": f"{D} mesh devices requested, {avail} visible "
                         f"(CPU targets force host devices automatically; "
                         f"was JAX initialized before the flag?)"}
    n_records = args.records or (1 << 18 if args.smoke else 1 << 22)
    n_keys = min(args.keys, n_records)
    batches = make_batches(n_records, n_keys, args.batch_size,
                           args.window_ms)
    (rps, fired, snaps, mid, digests, phases, bytes_, shard_ns,
     op) = run_tpu_native(
        batches, args.window_ms, args.checkpoint_every,
        emit_tier=args.emit_tier, device_sync=args.device_sync,
        timed_passes=2 if args.smoke else 3,
        pipeline_depth=args.pipeline_depth,
        native_shards=args.native_shards, mesh_devices=D,
        device_probe=args.device_probe, superbatch=args.superbatch,
        # size the ring to the workload so the key-group-range blocks are
        # POPULATED on every device (capacity-sized blocks would park all
        # live rows on shard 0 at small key counts)
        key_capacity=n_keys)
    replay_ok = replay_check(batches, args.window_ms, mid, digests,
                             args.emit_tier, args.device_sync,
                             pipeline_depth=args.pipeline_depth,
                             native_shards=args.native_shards,
                             mesh_devices=D, key_capacity=n_keys,
                             device_probe=args.device_probe,
                             superbatch=args.superbatch)
    ns = phases.pop("elapsed", 1)
    per_shard_ms = [round(v / 1e6, 1)
                    for v in shard_ns.get("probe_mirror", [])]
    dp = op.device_probe_stats()
    detail = {
        "mesh_devices": D,
        "platform": jax.devices()[0].platform,
        "phases_ms": {k: round(v / 1e6, 1)
                      for k, v in sorted(phases.items())},
        "probe_mirror_shard_ms": per_shard_ms,
        "elapsed_ms": round(ns / 1e6, 1),
        "h2d_mb": round(bytes_.get("h2d", 0) / 1e6, 2),
        "windows_fired": fired,
        "snapshots_in_timed_run": snaps,
        "restore_replay_ok": replay_ok,
        "emit_tier": args.emit_tier,
        "device_sync": op.device_sync_mode,
        "device_probe": "on" if dp["enabled"] else "off",
        "probe_hit_rate": (round(dp["probe_hit_rate"], 4)
                           if dp["probe_hit_rate"] is not None else None),
        # fused staging on the mesh: the host super-pass + one exchange
        # dispatch per super-batch (the scan lane is structurally off)
        "fused": {k: (bool(v) if k == "enabled" else v)
                  for k, v in op.fused_stats().items()
                  if k in ("enabled", "depth", "flushes",
                           "host_super_passes", "hot_dispatches")},
        # --mesh-devices 1 is the single-chip leg of the comparison: the
        # plain operator has no shard layout, its "manifest" is one block
        "shard_manifest": ([
            {"shard": d, "rows": list(op.shard_layout().row_range(d))}
            for d in range(D)] if hasattr(op, "shard_layout")
            else [{"shard": 0, "rows": [0, op._K]}]),
    }
    return {
        "metric": f"records/sec/pod (1M-key tumbling sum, "
                  f"{detail['platform']} mesh x{D}, checkpointing every "
                  f"{args.checkpoint_every} batches)",
        "value": round(rps, 1),
        "unit": "records/sec",
        "records_per_sec_pod": round(rps, 1),
        "records_per_sec_chip": round(rps / D, 1),
        "ok": replay_ok,
        "details": detail,
    }


def check_mesh_budget(result: dict, budget: dict) -> list:
    """``--mesh-devices`` result vs the BENCH_BUDGET ``mesh_cpu`` section:
    a pod-throughput floor, per-phase ceilings, and a per-shard probe
    share ceiling — the probe_mirror wall must actually be DECOMPOSED
    (one shard hogging the whole wall means the sharding is fictional)."""
    viol = []
    if "error" in result:
        return [result["error"]]
    floor = budget.get("min_rps_pod")
    if floor is not None and result["records_per_sec_pod"] < floor:
        viol.append(f"rec/s/pod {result['records_per_sec_pod']:.0f} < "
                    f"floor {floor:.0f}")
    phases = result["details"]["phases_ms"]
    for name, cap in budget.get("max_phase_ms", {}).items():
        got = phases.get(name)
        if got is not None and got > cap:
            viol.append(f"phase {name} {got}ms > budget {cap}ms")
    share_cap = budget.get("max_shard_probe_share")
    per_shard = result["details"].get("probe_mirror_shard_ms") or []
    live = [v for v in per_shard if v > 0]
    # exempt single-live-shard runs: EXACT zeros only come from the serial
    # C pass (sub-threshold batches write shard_ns[0]=total, rest 0 by
    # contract).  A genuinely parked fold cannot masquerade: in the
    # sharded pass every shard scans all records (the ownership check is
    # per-record), so even a shard owning zero slots reports nonzero ns
    # and the share check sees it
    if share_cap is not None and len(live) > 1:
        share = max(live) / sum(live)
        if share > share_cap:
            viol.append(
                f"probe shard share {share:.0%} > ceiling {share_cap:.0%} "
                f"(per-shard ms {per_shard}: the probe_mirror wall is not "
                f"decomposed)")
    if not result.get("ok"):
        viol.append("restore/replay check failed")
    return viol


def fused_equivalence_check(window_ms: int) -> bool:
    """Fused on/off digest equality, asserted IN the run (ISSUE-11): a
    small prefix of the headline stream drains through (a) the unfused
    path, (b) the fused host super-pass, and (c) the forced scan lane
    (device probe on + superbatch), and all three must produce identical
    fire digests AND identical mid-run snapshot bytes.  The mirror tier's
    f64/i64 accumulation is exact for f32 inputs, so this is equality,
    not tolerance."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    eq_batches = make_batches(1 << 16, 1 << 13, 1 << 13, window_ms,
                              seed=41)

    def drain(superbatch, device_probe):
        op = _build_op(window_ms, "host", "deferred",
                       pipeline_depth=0, native_shards=1,
                       key_capacity=1 << 13, device_probe=device_probe,
                       superbatch=superbatch)
        out = []
        sbytes = None
        for i, (k, v, ts) in enumerate(eq_batches):
            out += op.process_batch(RecordBatch({"k": k, "v": v},
                                                timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
            if i == len(eq_batches) // 2:
                op.prepare_snapshot_pre_barrier()
                snap = op.snapshot_state()
                sbytes = (snap["counts"].tobytes(),
                          tuple(np.asarray(l).tobytes()
                                for l in snap["leaves"]))
        out += op.end_input()
        return _fire_digests(out), sbytes

    base = drain(1, "off")
    return drain(8, "off") == base and drain(4, "on") == base


def check_fused_budget(result: dict, budget: dict,
                       smoke: bool = False) -> list:
    """Fused-lane gate (BENCH_BUDGET ``fused_cpu``/``fused_device``): the
    in-run fused on/off digest equivalence is unconditional (divergent
    digests never exit 0), ``max_dispatches_per_batch`` pins the
    one-dispatch claim (steady-state warm-key super-batches must not leak
    per-stage dispatches back in), and ``min_vs_numpy`` floors the CPU
    fallback tier's ratio on full runs (smoke is one batch of fixed
    costs)."""
    viol = []
    d = result["details"].get("fused") or {}
    if not d.get("equivalence_ok"):
        viol.append("fused on/off digest equivalence failed (fire digests "
                    "or snapshot bytes diverge between the staged and "
                    "per-batch paths)")
    # the one-dispatch ceiling gates the FUSED lane's claim only: a run
    # whose lane resolved (or was forced) off never promised amortized
    # dispatch — e.g. per-batch probe+miss-update is structurally 2/batch
    cap = budget.get("max_dispatches_per_batch")
    dpb = d.get("dispatches_per_batch")
    if (cap is not None and dpb is not None and d.get("enabled")
            and dpb > cap):
        viol.append(f"hot-path dispatches/batch {dpb} > ceiling {cap} "
                    f"(the megastep is not amortizing dispatch)")
    floor = budget.get("min_vs_numpy")
    vs = result.get("vs_numpy_baseline")
    if floor is not None and not smoke and vs is not None and vs < floor:
        viol.append(f"vs_numpy_baseline {vs} < fused floor {floor}")
    return viol


def check_budget(result: dict, budget: dict) -> list:
    """Compare one bench result against a BENCH_BUDGET.json section; returns
    human-readable violations (empty = pass).  The in-repo regression gate
    (VERDICT r3 weak #3): throughput floor, p99 ceiling, per-phase ceilings,
    plus (where budgeted) a vs-numpy floor — the framework must not lose to
    flat single-core numpy on its own fallback tier — and a probe_mirror
    share-of-elapsed ceiling guarding the pipelined host path."""
    viol = []
    if result["value"] < budget["min_rps"]:
        viol.append(f"rec/s {result['value']:.0f} < floor "
                    f"{budget['min_rps']:.0f}")
    p99 = result["p99_fire_latency_ms"]
    if p99 > budget["max_p99_ms"]:
        viol.append(f"p99 fire latency {p99}ms > ceiling "
                    f"{budget['max_p99_ms']}ms")
    phases = result["details"]["phases_ms"]
    for name, cap in budget.get("max_phase_ms", {}).items():
        got = phases.get(name)
        if got is not None and got > cap:
            viol.append(f"phase {name} {got}ms > budget {cap}ms")
    floor = budget.get("min_vs_numpy")
    vs_np = result.get("vs_numpy_baseline")
    if floor is not None and vs_np is not None and vs_np < floor:
        viol.append(f"vs_numpy_baseline {vs_np} < floor {floor}")
    frac = budget.get("max_probe_mirror_frac")
    elapsed = result["details"].get("elapsed_ms")
    pm = phases.get("probe_mirror")
    if frac is not None and pm is not None and elapsed:
        share = pm / elapsed
        if share > frac:
            viol.append(f"probe_mirror {pm}ms is {share:.0%} of elapsed "
                        f"{elapsed}ms > ceiling {frac:.0%}")
    hr_floor = budget.get("min_probe_hit_rate")
    hr = result["details"].get("probe_hit_rate")
    if hr_floor is not None and result["details"].get("device_probe") == "on" \
            and hr is not None and hr < hr_floor:
        viol.append(f"probe_hit_rate {hr} < floor {hr_floor} (the device "
                    f"probe is not absorbing the warm-key steady state)")
    return viol


def run_trace_bench(args, batches) -> dict:
    """The --trace legs: a tracing-OFF and a tracing-ON run of the SAME
    headline workload (same warmup/checkpoint cadence, best-of-2 each,
    back-to-back so host drift mostly cancels), plus the Chrome
    trace-event artifact from the ON leg's span journal.  Returns the
    ``details["trace"]`` dict; the artifact itself is written to
    ``args.trace``."""
    from flink_tpu.observability import tracing

    kw = dict(emit_tier=args.emit_tier, device_sync=args.device_sync,
              timed_passes=2, pipeline_depth=args.pipeline_depth,
              native_shards=args.native_shards,
              device_probe=args.device_probe)
    off_rps = run_tpu_native(batches, args.window_ms,
                             args.checkpoint_every, **kw)[0]
    journal = tracing.install(tracing.SpanJournal(capacity=1 << 17))
    try:
        on_rps = run_tpu_native(batches, args.window_ms,
                                args.checkpoint_every, **kw)[0]
    finally:
        tracing.uninstall()
    snap = journal.snapshot()
    spans = snap["spans"]
    hot = sum(1 for s in spans if s[4] == "hot_stage")
    ckpt = sum(1 for s in spans if s[4] == "checkpoint")
    ratio = on_rps / off_rps if off_rps else 0.0
    return {"journal_snapshot": snap,
            "tracing_off_rps": round(off_rps, 1),
            "tracing_on_rps": round(on_rps, 1),
            "throughput_ratio": round(ratio, 4),
            "spans": len(spans), "dropped_spans": snap["dropped"],
            "hot_stage_spans": hot, "checkpoint_spans": ckpt}


def write_trace_artifact(path: str, trace: dict, latency_ms: dict) -> dict:
    """Write the Perfetto-loadable trace-event JSON: the ON leg's spans
    plus the fire-latency histogram summary (the ``window_fire_ms``
    percentiles) embedded both as an instant event and in ``otherData``.
    Returns the summary that lands in the bench result details."""
    from flink_tpu.observability import tracing

    snap = trace.pop("journal_snapshot")
    events = tracing.to_chrome(snap, pid=0, process_name="bench")
    lat_summary = {k: v for k, v in latency_ms.items()}
    events.append({"name": "latency.window_fire", "cat": "latency",
                   "ph": "i", "s": "g", "pid": 0, "tid": 0,
                   "ts": snap["anchor_wall_us"], "args": lat_summary})
    artifact = {
        "traceEvents": events, "displayTimeUnit": "ms",
        "otherData": {
            "latency_histograms": {"window_fire_ms": lat_summary},
            "tracing_off_rps": trace["tracing_off_rps"],
            "tracing_on_rps": trace["tracing_on_rps"],
            "throughput_ratio": trace["throughput_ratio"],
            "dropped_spans": trace["dropped_spans"]}}
    with open(path, "w") as f:
        json.dump(artifact, f)
    # count only a summary that carries actual samples — a zero-sample
    # dict would let the --check structural gate pass on a vacuous
    # artifact (no windows fired in the timed run)
    n_summaries = 1 if lat_summary.get("samples") else 0
    return {**trace, "latency_summaries": n_summaries, "path": path}


def check_trace_budget(trace: dict, budget: dict,
                       smoke: bool = False) -> list:
    """trace_cpu gate: tracing must stay within the budgeted throughput
    cost (<5% by default), and the artifact must be STRUCTURALLY useful —
    hot-stage phase spans, checkpoint lifecycle spans and at least one
    latency histogram summary, none of it silently truncated away.
    The throughput ratio only gates FULL-size runs: at smoke size the
    fixed per-pass costs (compile, first-fire) dominate and the on/off
    ratio is noise; the structural checks gate unconditionally."""
    viol = []
    floor = budget.get("min_throughput_ratio", 0.95)
    if not smoke and trace["throughput_ratio"] < floor:
        viol.append(f"tracing-on throughput is "
                    f"{trace['throughput_ratio']:.3f}x tracing-off "
                    f"< floor {floor} (tracing must stay ~free)")
    if trace.get("hot_stage_spans", 0) <= 0:
        viol.append("trace contains no hot-stage phase spans")
    if trace.get("checkpoint_spans", 0) <= 0:
        viol.append("trace contains no checkpoint lifecycle spans")
    if trace.get("latency_summaries", 0) < 1:
        viol.append("trace contains no latency histogram summary")
    return viol


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small fast run")
    ap.add_argument("--records", type=int, default=0)
    ap.add_argument("--keys", type=int, default=1_000_000)
    ap.add_argument("--batch-size", type=int, default=1 << 18)
    ap.add_argument("--window-ms", type=int, default=5000)
    ap.add_argument("--checkpoint-every", type=int, default=16,
                    help="snapshot every N batches inside the timed run")
    ap.add_argument("--emit-tier", default="host",
                    choices=["host", "device"])
    ap.add_argument("--device-sync", default="auto",
                    choices=["auto", "scatter", "deferred"],
                    help="device replica cadence for the host emit tier: "
                         "per-batch scatter, deferred refresh, or "
                         "transport-calibrated auto (utils/transport.py)")
    ap.add_argument("--skip-verify", action="store_true",
                    help="skip the post-run device-vs-mirror download check")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero if the result violates "
                         "BENCH_BUDGET.json (regression gate)")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="hot-path software pipeline depth (0 = serial "
                         "probe->dispatch->mirror; default 1 overlaps the "
                         "hot stage with the driver + device compute)")
    ap.add_argument("--native-shards", type=int, default=0,
                    help="native probe shard count (0 = auto: "
                         "FLINK_TPU_NATIVE_SHARDS or one per core up to 4)")
    ap.add_argument("--superbatch", type=int, default=0, metavar="N",
                    help="one-dispatch fused megastep (ISSUE-11): stage N "
                         "micro-batches and advance them in ONE pass — a "
                         "device-side lax.scan over donated buffers when "
                         "the device probe is active, one concatenated "
                         "fused C probe+fold on the host tier.  0 = auto "
                         "(measured process-wide A/B, like "
                         "--pipeline-depth/--device-probe), 1 = off; "
                         "details land in details.fused and with --check "
                         "gate against BENCH_BUDGET.json fused_cpu")
    ap.add_argument("--device-probe", default="auto",
                    choices=["auto", "on", "off"],
                    help="device-resident key probe (state/device_keyindex):"
                         " resolve warm keys inside the jitted step so the "
                         "host C fold touches only misses.  auto runs a "
                         "measured A/B calibration (the probe usually loses "
                         "on CPU-forced runs and wins on real "
                         "accelerators); on/off force")
    ap.add_argument("--profile", metavar="PATH", default=None,
                    help="write the per-phase breakdown (phase_ns, "
                         "phase_bytes, phases_ms) of the winning timed pass "
                         "to PATH as JSON; the device step is additionally "
                         "annotated for jax.profiler traces "
                         "('window_agg.device_step')")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="end-to-end tracing artifact (ISSUE-10): run a "
                         "tracing-off and a tracing-on leg of the headline "
                         "workload and write the ON leg's span journal as "
                         "Chrome trace-event JSON (Perfetto-loadable: "
                         "hot-stage phase spans, checkpoint lifecycle "
                         "spans, latency histogram summary) to PATH; with "
                         "--check the tracing-on/off throughput ratio "
                         "gates against BENCH_BUDGET.json trace_cpu")
    ap.add_argument("--mesh-devices", type=int, default=0, metavar="N",
                    help="run the SHARDED hot path as one logical window "
                         "operator over an N-device mesh (state in "
                         "key-group-range blocks, records routed by an "
                         "on-device all_to_all, probe sharded per device) "
                         "and report records/sec/pod + records/sec/chip + "
                         "the per-shard probe breakdown.  On CPU targets "
                         "the N host devices are forced automatically "
                         "(--xla_force_host_platform_device_count); with "
                         "--check the result gates against the "
                         "BENCH_BUDGET.json mesh_cpu section")
    ap.add_argument("--cep", action="store_true",
                    help="standalone CEP workload: fraud-detection-style "
                         "pattern over the 1M-key stream through the "
                         "vectorized NFA kernel (cep/vectorized.py), "
                         "reporting matches/sec + partials high-water + "
                         "the measured speedup over the interpreted NFA; "
                         "with --check gates against the BENCH_BUDGET.json "
                         "cep_cpu section")
    ap.add_argument("--queryable", action="store_true",
                    help="standalone serving-tier workload (ISSUE-13): "
                         "--qps-clients pooled clients sustain "
                         "--qps-target batched lookups/sec (live + "
                         "checkpoint consistency) over the binary "
                         "columnar wire with client-side key-group "
                         "routing against the running 1M-key window job; "
                         "reports lookups/sec + client p50/p99 + "
                         "server-side serve p50/p99 + replica lag + the "
                         "job's throughput under load; with --check "
                         "gates against BENCH_BUDGET.json queryable_cpu")
    ap.add_argument("--qps-clients", type=int, default=0,
                    help="--queryable client PROCESS count (0 = auto: 4 "
                         "full, 2 smoke) — clients run out-of-process "
                         "like production readers; only the server "
                         "shares the job's process")
    ap.add_argument("--qps-target", type=int, default=150_000,
                    help="--queryable aggregate sustained lookups/sec "
                         "target the client fleet paces itself to (0 = "
                         "unthrottled max-rate mode)")
    ap.add_argument("--qps-batch-keys", type=int, default=1024,
                    help="--queryable keys per batched request")
    ap.add_argument("--_qps-client", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--_qps-host", default="127.0.0.1",
                    help=argparse.SUPPRESS)
    ap.add_argument("--_qps-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--_qps-seed", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--_qps-interval-us", type=float, default=0.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--paging-cap", type=int, default=0,
                    help="also run one cold-key-paging pass (device tier, "
                         "K_cap=N < key count) and report rps + "
                         "resident/spilled occupancy in details.paging")
    ap.add_argument("--config", type=int, default=2, choices=[1, 2, 3, 4, 5],
                    help="BASELINE.md config: 1=WordCount, 2=1M-key "
                         "tumbling (headline, default), 3=sliding "
                         "multi-field, 4=session+Zipf, 5=SQL TUMBLE/HOP")
    ap.add_argument("--checkpoint-interval", type=int, metavar="MS",
                    default=0,
                    help="standalone checkpoint-under-backpressure run: "
                         "trigger checkpoints every MS milliseconds on a "
                         "MiniCluster window job while seeded SlowConsumer"
                         "/SlowDisk chaos injects backpressure; reports "
                         "checkpoint duration + persisted in-flight bytes "
                         "and exits nonzero if a checkpoint misses the "
                         "checkpoint_backpressure budget; also runs the "
                         "incremental-checkpoint leg (ISSUE-16): delta "
                         "bytes vs a full snapshot at 10%% key churn, "
                         "increments-per-base and chain-resolve recovery "
                         "time, gated by checkpoint_incremental (the "
                         "chain-restore digest-equality check is "
                         "unconditional)")
    ap.add_argument("--autoscale", action="store_true",
                    help="standalone reactive-autoscaler run (ISSUE-14): a "
                         "diurnal load-curve source over a keyed window "
                         "job with a fixed per-dequeue consumer cost; the "
                         "ReactiveAutoscaler rescales 2->4 at the peak "
                         "and back after it via unaligned checkpoints "
                         "with channel-state redistribution (no drain); "
                         "reports rescale latency, throughput recovery "
                         "time and records lost/duplicated (must be 0); "
                         "with --check gates against BENCH_BUDGET.json "
                         "rescale_cpu")
    ap.add_argument("--scenario", default="",
                    help="scenario suite (ISSUE-15): run one named "
                         "end-to-end exactly-once application "
                         "(fraud_detection, sessionized_analytics, "
                         "feature_store) or 'all' — the diurnal load "
                         "curve drives the job under the reactive "
                         "autoscaler with nemeses injected at the peak "
                         "and routed queryable readers; the committed "
                         "transactional output must be exactly-once and "
                         "digest-identical to an unfaulted control; with "
                         "--check gates each scenario against its "
                         "BENCH_BUDGET.json scenario_*_cpu section")
    ap.add_argument("--ha-kill", action="store_true",
                    help="coordinator high availability under fire "
                         "(ISSUE-20): run one scenario (default "
                         "fraud_detection; pick with --scenario) under a "
                         "FileHaStore leader lease, kill the leader's "
                         "lease renewal at the diurnal peak while it "
                         "keeps executing as a zombie, and have a "
                         "standby take over at epoch+1, fence the "
                         "zombie's checkpoint completions and 2PC "
                         "commits, and recover the job from the "
                         "HA-store pointer (increment chains included); "
                         "committed output must be exactly-once and "
                         "digest-identical to an unfaulted control; "
                         "with --check gates against BENCH_BUDGET.json "
                         "ha_cpu")
    ap.add_argument("--inject-wedge", action="store_true",
                    help="standalone recovery smoke: wedge the hot-path "
                         "dispatch with a deterministic chaos schedule and "
                         "drive the shared watchdog/quarantine/degrade/"
                         "heal/re-promote path end-to-end; exits nonzero "
                         "if the cycle or digest equality fails")
    args = ap.parse_args()

    if getattr(args, "_qps_client"):
        # hidden worker mode: one out-of-process queryable client of the
        # --queryable bench (never imports jax — stays off the job's GIL)
        sys.exit(_qps_client_main(args))

    if args.trace and (args.cep or args.queryable or args.mesh_devices
                       or args.config != 2 or args.inject_wedge
                       or args.checkpoint_interval or args.autoscale
                       or args.scenario or args.ha_kill):
        # --trace measures the HEADLINE single-chip workload's on/off legs;
        # the dedicated-mode branches below exit before the trace block, so
        # refuse loudly instead of silently writing no artifact
        print("# ERROR: --trace applies to the headline bench only; drop "
              "--cep/--queryable/--mesh-devices/--config to produce the "
              "trace artifact", file=sys.stderr)
        sys.exit(2)

    if args.inject_wedge:
        # standalone smoke with its own fixed 1s window: the cycle under
        # test (wedge -> degrade -> heal -> re-promote) is window-size
        # independent, and the headline flags stay untouched
        result = run_wedge_smoke()
        print(json.dumps(result))
        sys.exit(0 if result["ok"] else 1)

    if args.checkpoint_interval:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_BUDGET.json")
        with open(path) as f:
            budgets = json.load(f)
        budget = budgets.get("checkpoint_backpressure", {})
        result = run_checkpoint_backpressure(
            args.checkpoint_interval,
            budget_ms=budget.get("max_duration_ms", 5000.0),
            min_completed=budget.get("min_completed", 1))
        # incremental leg (ISSUE-16): delta bytes vs full at 10% churn,
        # increments-per-base, chain-resolve recovery time, digest gate
        inc = run_incremental_checkpoint_bench(smoke=args.smoke)
        inc_viol = check_incremental_budget(
            inc, budgets.get("checkpoint_incremental", {}),
            smoke=args.smoke)
        result["incremental"] = inc
        result["ok"] = bool(result["ok"] and inc["ok"] and not inc_viol)
        print(json.dumps(result))
        if not result["ok"]:
            print(f"# BUDGET VIOLATION: checkpoint under backpressure — "
                  f"max duration {result['max_duration_ms']} ms vs budget "
                  f"{result['budget_ms']} ms, state {result['state']}, "
                  f"{result['completed_checkpoints']} completed",
                  file=sys.stderr)
        for v in inc_viol:
            print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
        sys.exit(0 if result["ok"] else 1)

    if args.ha_kill:
        result = run_ha_kill_bench(args)
        print(json.dumps(result))
        print(f"# ha-kill: {json.dumps(result.get('ha_kill', {}))}",
              file=sys.stderr)
        if args.check:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budget = json.load(f).get("ha_cpu", {})
            viol = check_ha_budget(result.get("ha_kill", {}), budget,
                                   smoke=args.smoke)
            for v in viol:
                print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
            sys.exit(1 if viol else 0)
        sys.exit(0 if result.get("ok") else 1)

    if args.scenario:
        result = run_scenario_bench(args)
        print(json.dumps(result))
        for s in result["scenarios"]:
            print(f"# scenario {s['scenario']}: {json.dumps(s)}",
                  file=sys.stderr)
        if args.check:
            from flink_tpu.scenarios import get_scenario
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budgets = json.load(f)
            viol = []
            for s in result["scenarios"]:
                section = get_scenario(s["scenario"]).budget_section
                viol += check_scenario_budget(s, budgets.get(section, {}),
                                              smoke=args.smoke)
            for v in viol:
                print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
            sys.exit(1 if viol else 0)
        sys.exit(0 if result["ok"] else 1)

    if args.autoscale:
        result = run_autoscale_bench(args)
        print(json.dumps(result))
        if args.check:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budget = json.load(f).get("rescale_cpu", {})
            viol = check_rescale_budget(result, budget, smoke=args.smoke)
            for v in viol:
                print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
            sys.exit(1 if viol else 0)
        sys.exit(0 if result.get("ok") else 1)

    if args.cep:
        result = run_cep_bench(args)
        print(json.dumps(result))
        print(f"# details: {json.dumps(result.get('details', {}))}",
              file=sys.stderr)
        if args.check:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budget = json.load(f).get("cep_cpu", {})
            viol = check_cep_budget(result, budget, smoke=args.smoke)
            for v in viol:
                print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
            sys.exit(1 if viol else 0)
        sys.exit(0 if result.get("ok") else 1)

    if args.queryable:
        result = run_queryable_bench(args)
        print(json.dumps(result))
        print(f"# details: {json.dumps(result.get('details', {}))}",
              file=sys.stderr)
        if args.check:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budget = json.load(f).get("queryable_cpu", {})
            viol = check_queryable_budget(result, budget, smoke=args.smoke)
            for v in viol:
                print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
            sys.exit(1 if viol else 0)
        sys.exit(0 if result.get("ok") else 1)

    if args.mesh_devices:
        result = run_mesh_bench(args)
        print(json.dumps(result))
        print(f"# details: {json.dumps(result.get('details', {}))}",
              file=sys.stderr)
        if args.check:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budgets = json.load(f)
            import jax
            tier = ("mesh_cpu" if jax.devices()[0].platform == "cpu"
                    else "mesh")
            budget = budgets.get(tier)
            if budget is not None and args.smoke:
                # smoke sizes are one batch of fixed costs: the structural
                # checks (shard share, phases, replay) still gate, the
                # full-run pod floor does not
                budget = {k: v for k, v in budget.items()
                          if k != "min_rps_pod"}
            # no budget section for this backend: the correctness checks
            # (restore/replay) still gate — a digest mismatch must never
            # exit 0 just because no perf floor is configured
            viol = check_mesh_budget(result, budget or {})
            for v in viol:
                print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
            sys.exit(1 if viol else 0)
        sys.exit(0 if result.get("ok") else 1)

    if args.config != 2:
        result = CONFIG_RUNNERS[args.config](args.smoke)
        print(json.dumps(result))
        if args.check:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BUDGET.json")
            with open(path) as f:
                budget = json.load(f).get(f"config{args.config}")
            if budget is not None:
                ok = result["value"] >= budget["min_rps"]
                if not ok:
                    print(f"# BUDGET VIOLATION: rec/s {result['value']:.0f}"
                          f" < floor {budget['min_rps']:.0f}",
                          file=sys.stderr)
                sys.exit(0 if ok else 1)
        return

    n_records = args.records or (1 << 18 if args.smoke else 1 << 24)
    n_keys = min(args.keys, n_records)
    batches = make_batches(n_records, n_keys, args.batch_size, args.window_ms)
    if args.native_shards == 0 and args.emit_tier == "host":
        # measured, not assumed: steal-heavy vCPUs can make the parallel
        # probe counterproductive (see _pick_native_shards)
        args.native_shards = _pick_native_shards()

    (tpu_rps, tpu_fired, snaps, mid, digests, phases, bytes_, _shard_ns,
     op) = run_tpu_native(batches, args.window_ms, args.checkpoint_every,
                          args.emit_tier, args.device_sync,
                          pipeline_depth=args.pipeline_depth,
                          native_shards=args.native_shards,
                          device_probe=args.device_probe,
                          superbatch=args.superbatch)
    replay_ok = replay_check(batches, args.window_ms, mid, digests,
                             args.emit_tier, args.device_sync,
                             pipeline_depth=args.pipeline_depth,
                             native_shards=args.native_shards,
                             device_probe=args.device_probe,
                             superbatch=args.superbatch)
    # fused on/off digest equality, asserted in THIS run (ISSUE-11): the
    # staged super-pass and the forced scan lane must match the per-batch
    # path exactly at small scale before the headline number counts
    fused_eq_ok = fused_equivalence_check(args.window_ms)
    # device-vs-mirror consistency: a REAL device download of the live
    # panes, compared against the host mirror (post-timing).  Under
    # deferred sync this validates the refresh round trip (upload ->
    # download -> compare); under scatter, continuous equality.
    mirror_ok = True
    if args.emit_tier == "host" and not args.skip_verify:
        mirror_ok = op.verify_mirror()

    # the device tier pays a real download per fire sample: cap the sample
    # count so an explicit --emit-tier device run finishes in minutes
    lat = measure_fire_latency(
        batches, args.window_ms,
        min_samples=(32 if args.smoke else 128)
        if args.emit_tier == "host" else 16,
        max_samples=256 if args.emit_tier == "host" else 16,
        emit_tier=args.emit_tier, device_sync=args.device_sync,
        pipeline_depth=args.pipeline_depth,
        native_shards=args.native_shards, device_probe=args.device_probe)

    # transparency: when the transport calibration sent the headline run
    # down the deferred path, ALSO measure the scatter path (the r1-r3
    # configuration) — single full pass, same warmup/checkpoint cadence —
    # so the cost of per-batch device sync on this link is on the record
    scatter_cmp = None
    if op.device_sync_mode == "deferred" and not args.smoke:
        s_rps, _f, _s, _m, _d, s_phases, s_bytes, _sn, _op2 = run_tpu_native(
            batches, args.window_ms, args.checkpoint_every,
            args.emit_tier, device_sync="scatter", timed_passes=1,
            pipeline_depth=args.pipeline_depth,
            native_shards=args.native_shards)
        s_ns = s_phases.pop("elapsed", 1)
        scatter_cmp = {
            "rps": round(s_rps, 1),
            "phases_ms": {k: round(v / 1e6, 1)
                          for k, v in sorted(s_phases.items())},
            "elapsed_ms": round(s_ns / 1e6, 1),
            "h2d_mb": round(s_bytes.get("h2d", 0) / 1e6, 2),
            "note": "single timed pass (headline gets best-of-3)",
        }

    # best-of-N on BOTH sides: the TPU path takes the max of three passes,
    # so the baselines get the same treatment — a one-sided max would bias
    # vs_baseline upward.  (The heap loop runs under a per-pass time budget,
    # so its rate is robust to a slow window; two passes suffice.)
    base_budget = 3.0 if args.smoke else 15.0
    base_rps = max(run_heap_baseline(batches, args.window_ms, base_budget)[0]
                   for _ in range(2))
    numpy_rps = max(run_numpy_baseline(batches, args.window_ms)[0]
                    for _ in range(3))

    import jax
    platform = jax.devices()[0].platform
    ns = phases.pop("elapsed", 1)
    detail = {
        "phases_ms": {k: round(v / 1e6, 1) for k, v in sorted(phases.items())},
        "elapsed_ms": round(ns / 1e6, 1),
        "h2d_mb": round(bytes_.get("h2d", 0) / 1e6, 2),
        "d2h_mb": round(bytes_.get("d2h", 0) / 1e6, 2),
        "snapshots_in_timed_run": snaps,
        "restore_replay_ok": replay_ok,
        "device_mirror_consistent": mirror_ok,
        "emit_tier": args.emit_tier,
        "windows_fired": tpu_fired,
        "latency_ms": {k: round(v, 2) if isinstance(v, float) else v
                       for k, v in lat.items()},
        "numpy_baseline_rps": round(numpy_rps, 1),
        "heap_baseline_rps": round(base_rps, 1),
        "device_sync": op.device_sync_mode,
        "pipeline_depth": args.pipeline_depth,
        "native_shards": op._nm_shards,
    }
    dp = op.device_probe_stats()
    detail["device_probe"] = "on" if dp["enabled"] else "off"
    if dp["enabled"]:
        detail["probe_hit_rate"] = (round(dp["probe_hit_rate"], 4)
                                    if dp["probe_hit_rate"] is not None
                                    else None)
        detail["miss_inserts"] = dp["miss_inserts"]
        detail["delta_d2h_mb"] = round(dp["delta_d2h_bytes"] / 1e6, 2)
    # ---- fused megastep accounting (ISSUE-11): the winning pass's staged
    # depth, scan dispatches, hot-path dispatches/batch (the one-dispatch
    # claim, gated by fused_cpu.max_dispatches_per_batch), compile counts
    # of the scan megasteps (sticky geometry ⇒ O(log) per run), and the
    # in-run fused on/off equivalence verdict
    fu = op.fused_stats()
    detail["fused"] = {
        "enabled": bool(fu["enabled"]),
        "superbatch": fu["depth"],
        "staged_batches": fu["staged_batches"],
        "flushes": fu["flushes"],
        "scan_dispatches": fu["scan_dispatches"],
        "scan_steps": fu["scan_steps"],
        "host_super_passes": fu["host_super_passes"],
        "dispatches_per_batch": round(
            fu["hot_dispatches"] / max(1, len(batches)), 3),
        "scan_compiles": op.fused_step_cache_size(),
        "equivalence_ok": fused_eq_ok,
    }
    from flink_tpu.utils import transport
    if transport.dispatch_ms_per_mb() is not None:
        detail["dispatch_ms_per_mb"] = round(transport.dispatch_ms_per_mb(), 2)
    if op.phase_bytes.get("h2d_refresh"):
        # the post-timing verify refresh (deferred sync's sync point)
        detail["h2d_refresh_mb"] = round(
            op.phase_bytes["h2d_refresh"] / 1e6, 2)
    if scatter_cmp is not None:
        detail["scatter_mode"] = scatter_cmp
    if args.paging_cap:
        # cold-key paging pass (state/paging.py): state larger than HBM on
        # the same workload — occupancy proves the ring ran as a cache
        p_rps, p_stats, p_phases = run_paged(
            batches, args.window_ms, args.checkpoint_every, args.paging_cap,
            pipeline_depth=args.pipeline_depth,
            native_shards=args.native_shards)
        detail["paging"] = {
            "rps": round(p_rps, 1),
            "resident_keys": p_stats["resident_keys"],
            "spilled_keys": p_stats["spilled_keys"],
            "evictions": p_stats["evictions"],
            "promotions": p_stats["promotions"],
            "capacity": p_stats["capacity"],
            "spill_mem_mb": round(p_stats["spill_mem_bytes"] / 1e6, 2),
            "spill_log_mb": round(p_stats["spill_log_bytes"] / 1e6, 2),
            "paging_ms": round(p_phases.get("paging", 0) / 1e6, 1),
        }
    trace_detail = None
    if args.trace:
        trace = run_trace_bench(args, batches)
        trace_detail = write_trace_artifact(args.trace, trace,
                                            detail["latency_ms"])
        detail["trace"] = trace_detail
    result = {
        "metric": f"records/sec/chip (1M-key tumbling sum, {platform}, "
                  f"checkpointing every {args.checkpoint_every} batches)",
        "value": round(tpu_rps, 1),
        "unit": "records/sec",
        "p99_fire_latency_ms": round(lat["p99"], 1),
        "latency_samples": lat["samples"],
        "vs_baseline": round(tpu_rps / base_rps, 3),
        "vs_numpy_baseline": round(tpu_rps / numpy_rps, 3),
        "details": detail,
    }
    print(json.dumps(result))
    print(f"# details: {json.dumps(detail)}", file=sys.stderr)
    if args.profile:
        # per-phase artifact (VERDICT #10): raw ns/bytes counters of the
        # WINNING timed pass plus the derived ms view — phase keys are the
        # operator's ``_phase`` names (asserted by tests/test_bench_gate)
        artifact = {
            "phase_ns": {k: int(v) for k, v in sorted(phases.items())},
            "phase_bytes": {k: int(v) for k, v in sorted(bytes_.items())},
            "phases_ms": detail["phases_ms"],
            "elapsed_ms": detail["elapsed_ms"],
            "device_sync": op.device_sync_mode,
            "pipeline_depth": args.pipeline_depth,
            "native_shards": op._nm_shards,
            "trace_annotation": "window_agg.device_step",
        }
        with open(args.profile, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        print(f"# profile written: {args.profile}", file=sys.stderr)
    if trace_detail is not None:
        print(f"# trace written: {args.trace} "
              f"({trace_detail['spans']} spans, "
              f"ratio {trace_detail['throughput_ratio']})", file=sys.stderr)
    if args.check:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_BUDGET.json")
        with open(path) as f:
            budgets = json.load(f)
        tier = "smoke" if args.smoke else "full"
        # CPU runs (JAX_PLATFORMS=cpu smoke, or a tunnel-less host) gate
        # against their own LOW-water marks — the accelerator floors would
        # always trip on a single CPU core; real-accelerator runs gate
        # against the *_device sections (ROADMAP item 2: device rounds
        # regress loudly, like CPU ones)
        if platform == "cpu" and f"{tier}_cpu" in budgets:
            tier = f"{tier}_cpu"
        elif platform != "cpu" and f"{tier}_device" in budgets:
            tier = f"{tier}_device"
        budget = budgets[tier]
        viol = check_budget(result, budget)
        fused_tier = ("fused_cpu" if platform == "cpu" else "fused_device")
        if fused_tier in budgets:
            viol += check_fused_budget(result, budgets[fused_tier],
                                       smoke=args.smoke)
        elif not fused_eq_ok:
            # no fused budget configured for this backend: the digest
            # equivalence still gates — divergence must never exit 0
            viol.append("fused on/off digest equivalence failed")
        if trace_detail is not None:
            # tracing-on must cost <5% throughput (trace_cpu section) and
            # the artifact must carry the spans the round needs
            viol += check_trace_budget(trace_detail,
                                       budgets.get("trace_cpu", {}),
                                       smoke=args.smoke)
        for v in viol:
            print(f"# BUDGET VIOLATION: {v}", file=sys.stderr)
        if not (replay_ok and mirror_ok):
            viol.append("correctness check failed")
            print("# BUDGET VIOLATION: restore/replay or mirror consistency "
                  "failed", file=sys.stderr)
        sys.exit(1 if viol else 0)


if __name__ == "__main__":
    main()
